#include "hashes.h"

#include <cstring>

#if defined(__AVX512F__)
#include <immintrin.h>
#endif

namespace tm {

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4)
// ---------------------------------------------------------------------------

namespace {

inline uint32_t rotr32(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

void sha256_block(uint32_t h[8], const uint8_t* p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
           g = h[6], hh = h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + S1 + ch + K256[i] + w[i];
    uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

}  // namespace

void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  size_t full = len / 64;
  for (size_t i = 0; i < full; i++) sha256_block(h, data + 64 * i);
  uint8_t tail[128];
  size_t rem = len - 64 * full;
  std::memcpy(tail, data + 64 * full, rem);
  tail[rem] = 0x80;
  size_t padded = (rem + 9 <= 64) ? 64 : 128;
  std::memset(tail + rem + 1, 0, padded - rem - 1 - 8);
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; i++) tail[padded - 1 - i] = uint8_t(bits >> (8 * i));
  sha256_block(h, tail);
  if (padded == 128) sha256_block(h, tail + 64);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(h[i] >> 24);
    out[4 * i + 1] = uint8_t(h[i] >> 16);
    out[4 * i + 2] = uint8_t(h[i] >> 8);
    out[4 * i + 3] = uint8_t(h[i]);
  }
}

// ---------------------------------------------------------------------------
// SHA-512 (FIPS 180-4), streaming
// ---------------------------------------------------------------------------

namespace {

inline uint64_t rotr64(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

const uint64_t K512[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

void sha512_block(uint64_t h[8], const uint8_t* p) {
  uint64_t w[80];
  for (int i = 0; i < 16; i++) {
    uint64_t v = 0;
    for (int j = 0; j < 8; j++) v = (v << 8) | p[8 * i + j];
    w[i] = v;
  }
  for (int i = 16; i < 80; i++) {
    uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
           g = h[6], hh = h[7];
  for (int i = 0; i < 80; i++) {
    uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = hh + S1 + ch + K512[i] + w[i];
    uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
    uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint64_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

}  // namespace

void sha512_init(Sha512Ctx* c) {
  static const uint64_t iv[8] = {
      0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
      0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
      0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  std::memcpy(c->h, iv, sizeof(iv));
  c->total = 0;
  c->buflen = 0;
}

void sha512_update(Sha512Ctx* c, const uint8_t* data, size_t len) {
  c->total += len;
  if (c->buflen) {
    size_t take = 128 - c->buflen;
    if (take > len) take = len;
    std::memcpy(c->buf + c->buflen, data, take);
    c->buflen += take;
    data += take;
    len -= take;
    if (c->buflen == 128) {
      sha512_block(c->h, c->buf);
      c->buflen = 0;
    }
  }
  while (len >= 128) {
    sha512_block(c->h, data);
    data += 128;
    len -= 128;
  }
  if (len) {
    std::memcpy(c->buf, data, len);
    c->buflen = len;
  }
}

void sha512_final(Sha512Ctx* c, uint8_t out[64]) {
  uint64_t bits = c->total * 8;
  uint8_t pad = 0x80;
  sha512_update(c, &pad, 1);
  if (c->buflen > 112) {
    std::memset(c->buf + c->buflen, 0, 128 - c->buflen);
    sha512_block(c->h, c->buf);
    c->buflen = 0;
  }
  std::memset(c->buf + c->buflen, 0, 112 - c->buflen);
  uint8_t lenbuf[16] = {0};
  for (int i = 0; i < 8; i++) lenbuf[15 - i] = uint8_t(bits >> (8 * i));
  // total was already advanced by padding updates; write length directly
  std::memcpy(c->buf + 112, lenbuf, 16);
  sha512_block(c->h, c->buf);
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 8; j++) out[8 * i + j] = uint8_t(c->h[i] >> (56 - 8 * j));
}

void sha512(const uint8_t* data, size_t len, uint8_t out[64]) {
  Sha512Ctx c;
  sha512_init(&c);
  sha512_update(&c, data, len);
  sha512_final(&c, out);
}

// ---------------------------------------------------------------------------
// RIPEMD-160
// ---------------------------------------------------------------------------

namespace {

inline uint32_t rol32(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

const int R1[80] = {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
                    7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
                    3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
                    1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
                    4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13};
const int R2[80] = {5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
                    6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
                    15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
                    8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
                    12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11};
const int S1[80] = {11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
                    7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
                    11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
                    11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
                    9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6};
const int S2[80] = {8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
                    9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
                    9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
                    15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
                    8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11};
const uint32_t KL[5] = {0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC,
                        0xA953FD4E};
const uint32_t KR[5] = {0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9,
                        0x00000000};

inline uint32_t f_rmd(int j, uint32_t x, uint32_t y, uint32_t z) {
  switch (j / 16) {
    case 0: return x ^ y ^ z;
    case 1: return (x & y) | (~x & z);
    case 2: return (x | ~y) ^ z;
    case 3: return (x & z) | (y & ~z);
    default: return x ^ (y | ~z);
  }
}

void rmd160_block(uint32_t h[5], const uint8_t* p) {
  uint32_t x[16];
  for (int i = 0; i < 16; i++)
    x[i] = uint32_t(p[4 * i]) | (uint32_t(p[4 * i + 1]) << 8) |
           (uint32_t(p[4 * i + 2]) << 16) | (uint32_t(p[4 * i + 3]) << 24);
  uint32_t al = h[0], bl = h[1], cl = h[2], dl = h[3], el = h[4];
  uint32_t ar = h[0], br = h[1], cr = h[2], dr = h[3], er = h[4];
  for (int j = 0; j < 80; j++) {
    uint32_t t = rol32(al + f_rmd(j, bl, cl, dl) + x[R1[j]] + KL[j / 16],
                       S1[j]) + el;
    al = el; el = dl; dl = rol32(cl, 10); cl = bl; bl = t;
    t = rol32(ar + f_rmd(79 - j, br, cr, dr) + x[R2[j]] + KR[j / 16],
              S2[j]) + er;
    ar = er; er = dr; dr = rol32(cr, 10); cr = br; br = t;
  }
  uint32_t t = h[1] + cl + dr;
  h[1] = h[2] + dl + er;
  h[2] = h[3] + el + ar;
  h[3] = h[4] + al + br;
  h[4] = h[0] + bl + cr;
  h[0] = t;
}

}  // namespace

void ripemd160(const uint8_t* data, size_t len, uint8_t out[20]) {
  uint32_t h[5] = {0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476,
                   0xC3D2E1F0};
  size_t full = len / 64;
  for (size_t i = 0; i < full; i++) rmd160_block(h, data + 64 * i);
  uint8_t tail[128];
  size_t rem = len - 64 * full;
  std::memcpy(tail, data + 64 * full, rem);
  tail[rem] = 0x80;
  size_t padded = (rem + 9 <= 64) ? 64 : 128;
  std::memset(tail + rem + 1, 0, padded - rem - 1 - 8);
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; i++) tail[padded - 8 + i] = uint8_t(bits >> (8 * i));
  rmd160_block(h, tail);
  if (padded == 128) rmd160_block(h, tail + 64);
  for (int i = 0; i < 5; i++) {
    out[4 * i] = uint8_t(h[i]);
    out[4 * i + 1] = uint8_t(h[i] >> 8);
    out[4 * i + 2] = uint8_t(h[i] >> 16);
    out[4 * i + 3] = uint8_t(h[i] >> 24);
  }
}

// ---------------------------------------------------------------------------
// RIPEMD-160, 16 independent equal-length messages per call (AVX-512:
// 16 uint32 lanes; vprolvd covers the per-step rotate amounts and one
// vpternlogd covers each round's boolean). The PartSet hot path hashes
// 64 KB parts — equal lengths, identical block counts and padding
// layout, so every lane stays in lockstep the whole way.
// ---------------------------------------------------------------------------
#if defined(__AVX512F__)

// GCC 12's masked-intrinsic fallback paths in avx512fintrin.h trip
// -Wmaybe-uninitialized on _mm512_rolv_epi32's pass-through operand —
// a known header false positive; keep the project build warning-clean
#pragma GCC diagnostic push
#pragma GCC diagnostic ignored "-Wmaybe-uninitialized"
#pragma GCC diagnostic ignored "-Wuninitialized"

namespace {

inline __m512i vf_rmd(int j, __m512i x, __m512i y, __m512i z) {
  // truth tables for imm8[(x<<2)|(y<<1)|z]
  switch (j / 16) {
    case 0: return _mm512_ternarylogic_epi32(x, y, z, 0x96);  // x^y^z
    case 1: return _mm512_ternarylogic_epi32(x, y, z, 0xCA);  // (x&y)|(~x&z)
    case 2: return _mm512_ternarylogic_epi32(x, y, z, 0x59);  // (x|~y)^z
    case 3: return _mm512_ternarylogic_epi32(x, y, z, 0xE4);  // (x&z)|(y&~z)
    default: return _mm512_ternarylogic_epi32(x, y, z, 0x2D);  // x^(y|~z)
  }
}

inline void rmd160_block_x16(__m512i h[5], const uint8_t* const p[16]) {
  alignas(64) uint32_t xbuf[16][16];  // [word][lane]
  for (int l = 0; l < 16; l++) {
    const uint8_t* q = p[l];
    for (int i = 0; i < 16; i++) {
      uint32_t w;
      std::memcpy(&w, q + 4 * i, 4);  // little-endian hosts only (x86)
      xbuf[i][l] = w;
    }
  }
  __m512i x[16];
  for (int i = 0; i < 16; i++) x[i] = _mm512_load_si512(&xbuf[i][0]);
  __m512i al = h[0], bl = h[1], cl = h[2], dl = h[3], el = h[4];
  __m512i ar = h[0], br = h[1], cr = h[2], dr = h[3], er = h[4];
  for (int j = 0; j < 80; j++) {
    __m512i t = _mm512_add_epi32(
        _mm512_add_epi32(al, vf_rmd(j, bl, cl, dl)),
        _mm512_add_epi32(x[R1[j]], _mm512_set1_epi32((int)KL[j / 16])));
    t = _mm512_add_epi32(_mm512_rolv_epi32(t, _mm512_set1_epi32(S1[j])), el);
    al = el; el = dl; dl = _mm512_rolv_epi32(cl, _mm512_set1_epi32(10));
    cl = bl; bl = t;
    t = _mm512_add_epi32(
        _mm512_add_epi32(ar, vf_rmd(79 - j, br, cr, dr)),
        _mm512_add_epi32(x[R2[j]], _mm512_set1_epi32((int)KR[j / 16])));
    t = _mm512_add_epi32(_mm512_rolv_epi32(t, _mm512_set1_epi32(S2[j])), er);
    ar = er; er = dr; dr = _mm512_rolv_epi32(cr, _mm512_set1_epi32(10));
    cr = br; br = t;
  }
  __m512i t = _mm512_add_epi32(h[1], _mm512_add_epi32(cl, dr));
  h[1] = _mm512_add_epi32(h[2], _mm512_add_epi32(dl, er));
  h[2] = _mm512_add_epi32(h[3], _mm512_add_epi32(el, ar));
  h[3] = _mm512_add_epi32(h[4], _mm512_add_epi32(al, br));
  h[4] = _mm512_add_epi32(h[0], _mm512_add_epi32(bl, cr));
  h[0] = t;
}

}  // namespace

void ripemd160_x16(const uint8_t* const msgs[16], size_t len,
                   uint8_t* out /* 16*20, lane-major */) {
  __m512i h[5];
  static const uint32_t IV[5] = {0x67452301, 0xEFCDAB89, 0x98BADCFE,
                                 0x10325476, 0xC3D2E1F0};
  for (int i = 0; i < 5; i++) h[i] = _mm512_set1_epi32((int)IV[i]);
  size_t full = len / 64;
  const uint8_t* p[16];
  for (size_t b = 0; b < full; b++) {
    for (int l = 0; l < 16; l++) p[l] = msgs[l] + 64 * b;
    rmd160_block_x16(h, p);
  }
  // padded tail: identical layout across lanes (same length)
  size_t rem = len - 64 * full;
  size_t padded = (rem + 9 <= 64) ? 64 : 128;
  uint8_t tails[16][128];
  uint64_t bits = uint64_t(len) * 8;
  for (int l = 0; l < 16; l++) {
    std::memcpy(tails[l], msgs[l] + 64 * full, rem);
    tails[l][rem] = 0x80;
    std::memset(tails[l] + rem + 1, 0, padded - rem - 1 - 8);
    for (int i = 0; i < 8; i++)
      tails[l][padded - 8 + i] = uint8_t(bits >> (8 * i));
  }
  for (int l = 0; l < 16; l++) p[l] = tails[l];
  rmd160_block_x16(h, p);
  if (padded == 128) {
    for (int l = 0; l < 16; l++) p[l] = tails[l] + 64;
    rmd160_block_x16(h, p);
  }
  alignas(64) uint32_t hs[5][16];
  for (int i = 0; i < 5; i++) _mm512_store_si512(&hs[i][0], h[i]);
  for (int l = 0; l < 16; l++)
    for (int i = 0; i < 5; i++) {
      uint32_t v = hs[i][l];
      out[20 * l + 4 * i] = uint8_t(v);
      out[20 * l + 4 * i + 1] = uint8_t(v >> 8);
      out[20 * l + 4 * i + 2] = uint8_t(v >> 16);
      out[20 * l + 4 * i + 3] = uint8_t(v >> 24);
    }
}

#pragma GCC diagnostic pop

#endif  // __AVX512F__

}  // namespace tm
