// Ed25519 strict (cofactorless) batch verification for the host data
// plane — the CPU fallback behind ops/gateway.Verifier and the native
// half of the hybrid batch-size policy (SURVEY.md §7 step 2).
//
// Field arithmetic is radix-2^51 over unsigned __int128 (the standard
// 5-limb representation for 64-bit targets); the group law uses extended
// Edwards coordinates with the complete formulas from RFC 8032 §5.1.4.
// Semantics mirror tendermint_tpu/crypto/ed25519.verify exactly:
// reject s >= L, reject non-canonical R.y >= p, reject invalid A,
// check [s]B == R + [h]A without multiplying by the cofactor.
#pragma once
#include <cstdint>

namespace tm {

// 1 if the signature verifies, else 0.
int ed25519_verify(const uint8_t pub[32], const uint8_t* msg, uint64_t msg_len,
                   const uint8_t sig[64]);

// Per-item verdicts for a batch — lane-identical to n ed25519_verify
// calls, with A decompressions deduped across repeated keys and run
// 8-wide when the host has AVX-512 IFMA.
void ed25519_verify_batch_items(const uint8_t* pubs, const uint8_t* sigs,
                                const uint8_t* msgs, const uint64_t* offsets,
                                int64_t n, uint8_t* out);

// Decompress a public key to affine (x, y) field elements serialized as
// 32-byte little-endian canonical values. Returns 1 on success.
// Batch variant: xy_out[i] = x||y (2x32 LE bytes), ok[i] = 1 on
// success. The (p-5)/8 power chains run 8-wide (AVX-512 IFMA) when the
// host supports it, with bit-identical results to the scalar path.
void ed25519_decompress_batch(const uint8_t* pubs, int64_t n,
                              uint8_t* xy_out, uint8_t* ok);

int ed25519_decompress(const uint8_t pub[32], uint8_t x_out[32],
                       uint8_t y_out[32]);

// h = SHA512(r || pub || msg) mod L, little-endian 32 bytes.
void ed25519_hram(const uint8_t r[32], const uint8_t pub[32],
                  const uint8_t* msg, uint64_t msg_len, uint8_t h_out[32]);

// Random-linear-combination batch verification (one Pippenger MSM over
// 2n+1 points). Returns 1 iff EVERY signature in the batch verifies
// under the same strict semantics as ed25519_verify, up to the standard
// 2^-128 soundness bound of the z-weighted combined equation; 0 means
// "at least one bad or undecided" — callers fall back to the per-item
// loop for exact lane verdicts.
int ed25519_verify_batch_rlc(const uint8_t* pubs, const uint8_t* sigs,
                             const uint8_t* msgs, const uint64_t* offsets,
                             int64_t n);

// Test seam for the MSM implementation choice: 0 = auto (vectorized
// when wide and the host has AVX-512 IFMA), 1 = force scalar, 2 = force
// vectorized. Differential tests drive both paths through it; both
// compute identical group elements.
void ed25519_set_msm_path(int path);
// test seam for the 8-wide per-item ladder (0 auto, 1 scalar, 2 8-wide)
void ed25519_set_items8_path(int path);

}  // namespace tm
