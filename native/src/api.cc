// C ABI for the Python runtime (ctypes). Batched entry points: one call
// marshals/verifies/hashes an entire batch — no per-item FFI overhead.
#include <cstdint>
#include <cstring>
#include <vector>

#include "ed25519.h"
#include "hashes.h"

using namespace tm;

extern "C" {

// ---------------------------------------------------------------------------
// hashes: msgs are concatenated in `data` with element i spanning
// [offsets[i], offsets[i+1]) — offsets has n+1 entries.
// ---------------------------------------------------------------------------

void tm_sha256_batch(const uint8_t* data, const uint64_t* offsets, int64_t n,
                     uint8_t* out /* n*32 */) {
  for (int64_t i = 0; i < n; i++)
    sha256(data + offsets[i], offsets[i + 1] - offsets[i], out + 32 * i);
}

void tm_ripemd160_batch(const uint8_t* data, const uint64_t* offsets,
                        int64_t n, uint8_t* out /* n*20 */) {
#if defined(__AVX512F__)
  // group equal-length runs and hash 16 per call in SIMD lanes — the
  // PartSet path (equal 64 KB parts) lands here almost entirely
  int64_t i = 0;
  while (i < n) {
    uint64_t len = offsets[i + 1] - offsets[i];
    int64_t j = i + 1;
    while (j < n && offsets[j + 1] - offsets[j] == len) j++;
    while (j - i >= 16) {
      const uint8_t* msgs[16];
      uint8_t lanes[16 * 20];
      for (int l = 0; l < 16; l++) msgs[l] = data + offsets[i + l];
      ripemd160_x16(msgs, (size_t)len, lanes);
      std::memcpy(out + 20 * i, lanes, sizeof(lanes));
      i += 16;
    }
    for (; i < j; i++)
      ripemd160(data + offsets[i], len, out + 20 * i);
  }
#else
  for (int64_t i = 0; i < n; i++)
    ripemd160(data + offsets[i], offsets[i + 1] - offsets[i], out + 20 * i);
#endif
}

// ---------------------------------------------------------------------------
// merkle: reference tree shape — odd splits give the LEFT side the extra
// leaf, split point (n+1)/2 (types/tx.go:33-46); hashes are RIPEMD-160
// over go-wire length-prefixed operands (merkle/simple.py parity).
// ---------------------------------------------------------------------------

namespace {

// encode_varint(len) for short non-negative lengths: [nbytes, big-endian...]
size_t put_len_prefix(uint8_t* out, uint64_t len) {
  if (len == 0) {
    out[0] = 0;
    return 1;
  }
  uint8_t tmp[8];
  int nb = 0;
  while (len) {
    tmp[nb++] = uint8_t(len & 0xff);
    len >>= 8;
  }
  out[0] = uint8_t(nb);
  for (int i = 0; i < nb; i++) out[1 + i] = tmp[nb - 1 - i];
  return 1 + nb;
}

void inner_hash(const uint8_t left[20], const uint8_t right[20],
                uint8_t out[20]) {
  uint8_t buf[44];
  size_t off = put_len_prefix(buf, 20);
  std::memcpy(buf + off, left, 20);
  off += 20;
  off += put_len_prefix(buf + off, 20);
  std::memcpy(buf + off, right, 20);
  off += 20;
  ripemd160(buf, off, out);
}

void tree_hash(const uint8_t* leaves, int64_t lo, int64_t hi,
               uint8_t out[20]) {
  if (hi - lo == 1) {
    std::memcpy(out, leaves + 20 * lo, 20);
    return;
  }
  int64_t mid = lo + (hi - lo + 1) / 2;
  uint8_t l[20], r[20];
  tree_hash(leaves, lo, mid, l);
  tree_hash(leaves, mid, hi, r);
  inner_hash(l, r, out);
}

}  // namespace

// leaf hashes: ripemd160(len-prefix || item) per item
void tm_merkle_leaf_hashes(const uint8_t* data, const uint64_t* offsets,
                           int64_t n, uint8_t* out /* n*20 */) {
  std::vector<uint8_t> buf;
  for (int64_t i = 0; i < n; i++) {
    uint64_t len = offsets[i + 1] - offsets[i];
    buf.resize(len + 9);
    size_t off = put_len_prefix(buf.data(), len);
    std::memcpy(buf.data() + off, data + offsets[i], len);
    ripemd160(buf.data(), off + len, out + 20 * i);
  }
}

// root from n 20-byte leaf digests (n >= 1)
void tm_merkle_root(const uint8_t* leaf_digests, int64_t n,
                    uint8_t out[20]) {
  tree_hash(leaf_digests, 0, n, out);
}

// ---------------------------------------------------------------------------
// ed25519
// ---------------------------------------------------------------------------

// batch verify: pubs n*32, sigs n*64, msgs concatenated + offsets.
// out[i] = 1 if valid.
void tm_ed25519_verify_batch(const uint8_t* pubs, const uint8_t* sigs,
                             const uint8_t* msgs, const uint64_t* offsets,
                             int64_t n, uint8_t* out) {
  ed25519_verify_batch_items(pubs, sigs, msgs, offsets, n, out);
}

// random-linear-combination batch verification: 1 iff ALL n signatures
// verify (strict semantics, 2^-128 soundness); 0 -> caller falls back to
// tm_ed25519_verify_batch for per-lane verdicts.
int tm_ed25519_verify_batch_rlc(const uint8_t* pubs, const uint8_t* sigs,
                                const uint8_t* msgs, const uint64_t* offsets,
                                int64_t n) {
  return ed25519_verify_batch_rlc(pubs, sigs, msgs, offsets, n);
}

// test seam: force the MSM implementation (0 auto, 1 scalar,
// 2 vectorized) so differential tests can drive both paths
void tm_ed25519_msm_path(int path) { ed25519_set_msm_path(path); }

// test seam: force the per-item ladder implementation (0 auto, 1 scalar,
// 2 8-wide IFMA) so differential tests can drive both paths
void tm_ed25519_items8_path(int path) { ed25519_set_items8_path(path); }

// batch h = SHA512(R || A || M) mod L for the TPU-kernel marshal
// (the per-item host cost the Python loop can't vectorize; one FFI call
// per batch, no per-item overhead). sigs n*64 (R = first 32 bytes),
// pubs n*32, msgs concatenated + offsets. h_out n*32 little-endian.
void tm_ed25519_hram_batch(const uint8_t* sigs, const uint8_t* pubs,
                           const uint8_t* msgs, const uint64_t* offsets,
                           int64_t n, uint8_t* h_out) {
  for (int64_t i = 0; i < n; i++)
    ed25519_hram(sigs + 64 * i, pubs + 32 * i, msgs + offsets[i],
                 offsets[i + 1] - offsets[i], h_out + 32 * i);
}

// batch pubkey decompress (for UNIQUE keys; callers dedupe + cache):
// xy_out[i] = x||y as 2*32 little-endian bytes, ok[i] = 1 on success.
void tm_ed25519_decompress_batch(const uint8_t* pubs, int64_t n,
                                 uint8_t* xy_out /* n*64 */,
                                 uint8_t* ok) {
  ed25519_decompress_batch(pubs, n, xy_out, ok);
}

}  // extern "C"
