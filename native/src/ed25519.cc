#include "ed25519.h"

#include <array>
#include <cstdio>
#include <cstring>
#include <vector>

#include "hashes.h"
#include "fe_ifma.h"

// NOTE: <random>/<string>/<unordered_map> are off-limits here — they pull
// in <wchar.h>, whose global `struct tm` collides with `namespace tm`.
// The RLC batch path below uses /dev/urandom + a small open-addressing
// cache instead.

namespace tm {
namespace {

// ---------------------------------------------------------------------------
// GF(2^255-19), radix 2^51, 5 limbs of uint64 (loose bound < 2^52)
// ---------------------------------------------------------------------------

typedef uint64_t fe[5];
typedef unsigned __int128 u128;

const uint64_t MASK51 = (1ULL << 51) - 1;

inline void fe_copy(fe o, const fe a) { std::memcpy(o, a, sizeof(fe)); }

inline void fe_zero(fe o) { std::memset(o, 0, sizeof(fe)); }

inline void fe_one(fe o) {
  fe_zero(o);
  o[0] = 1;
}

inline void fe_add(fe o, const fe a, const fe b) {
  for (int i = 0; i < 5; i++) o[i] = a[i] + b[i];
}

// o = a - b, with 2p bias to stay non-negative (limbs < 2^52 each side)
inline void fe_sub(fe o, const fe a, const fe b) {
  // 2p in radix 2^51
  o[0] = a[0] + 0xFFFFFFFFFFFDAULL - b[0];
  o[1] = a[1] + 0xFFFFFFFFFFFFEULL - b[1];
  o[2] = a[2] + 0xFFFFFFFFFFFFEULL - b[2];
  o[3] = a[3] + 0xFFFFFFFFFFFFEULL - b[3];
  o[4] = a[4] + 0xFFFFFFFFFFFFEULL - b[4];
}

void fe_carry(fe o) {
  uint64_t c;
  c = o[0] >> 51; o[0] &= MASK51; o[1] += c;
  c = o[1] >> 51; o[1] &= MASK51; o[2] += c;
  c = o[2] >> 51; o[2] &= MASK51; o[3] += c;
  c = o[3] >> 51; o[3] &= MASK51; o[4] += c;
  c = o[4] >> 51; o[4] &= MASK51; o[0] += 19 * c;
  c = o[0] >> 51; o[0] &= MASK51; o[1] += c;
}

void fe_mul(fe o, const fe a, const fe b) {
  u128 t0 = (u128)a[0] * b[0] + (u128)(19 * a[1]) * b[4] +
            (u128)(19 * a[2]) * b[3] + (u128)(19 * a[3]) * b[2] +
            (u128)(19 * a[4]) * b[1];
  u128 t1 = (u128)a[0] * b[1] + (u128)a[1] * b[0] + (u128)(19 * a[2]) * b[4] +
            (u128)(19 * a[3]) * b[3] + (u128)(19 * a[4]) * b[2];
  u128 t2 = (u128)a[0] * b[2] + (u128)a[1] * b[1] + (u128)a[2] * b[0] +
            (u128)(19 * a[3]) * b[4] + (u128)(19 * a[4]) * b[3];
  u128 t3 = (u128)a[0] * b[3] + (u128)a[1] * b[2] + (u128)a[2] * b[1] +
            (u128)a[3] * b[0] + (u128)(19 * a[4]) * b[4];
  u128 t4 = (u128)a[0] * b[4] + (u128)a[1] * b[3] + (u128)a[2] * b[2] +
            (u128)a[3] * b[1] + (u128)a[4] * b[0];
  uint64_t c;
  uint64_t r0 = (uint64_t)t0 & MASK51; c = (uint64_t)(t0 >> 51);
  t1 += c;
  uint64_t r1 = (uint64_t)t1 & MASK51; c = (uint64_t)(t1 >> 51);
  t2 += c;
  uint64_t r2 = (uint64_t)t2 & MASK51; c = (uint64_t)(t2 >> 51);
  t3 += c;
  uint64_t r3 = (uint64_t)t3 & MASK51; c = (uint64_t)(t3 >> 51);
  t4 += c;
  uint64_t r4 = (uint64_t)t4 & MASK51; c = (uint64_t)(t4 >> 51);
  r0 += 19 * c;
  c = r0 >> 51; r0 &= MASK51; r1 += c;
  o[0] = r0; o[1] = r1; o[2] = r2; o[3] = r3; o[4] = r4;
}

// dedicated squaring: the symmetric cross terms halve the 64x64 multiply
// count (15 vs fe_mul's 25). Squarings dominate decompression's
// (p-5)/8 exponentiation, which is ~a third of the batch-verify profile.
void fe_sq(fe o, const fe a) {
  uint64_t a0_2 = 2 * a[0], a1_2 = 2 * a[1];
  uint64_t a1_38 = 38 * a[1], a2_38 = 38 * a[2], a3_38 = 38 * a[3];
  uint64_t a3_19 = 19 * a[3], a4_19 = 19 * a[4];
  u128 t0 = (u128)a[0] * a[0] + (u128)a1_38 * a[4] + (u128)a2_38 * a[3];
  u128 t1 = (u128)a0_2 * a[1] + (u128)a2_38 * a[4] + (u128)a3_19 * a[3];
  u128 t2 = (u128)a0_2 * a[2] + (u128)a[1] * a[1] + (u128)a3_38 * a[4];
  u128 t3 = (u128)a0_2 * a[3] + (u128)a1_2 * a[2] + (u128)a4_19 * a[4];
  u128 t4 = (u128)a0_2 * a[4] + (u128)a1_2 * a[3] + (u128)a[2] * a[2];
  uint64_t c;
  uint64_t r0 = (uint64_t)t0 & MASK51; c = (uint64_t)(t0 >> 51);
  t1 += c;
  uint64_t r1 = (uint64_t)t1 & MASK51; c = (uint64_t)(t1 >> 51);
  t2 += c;
  uint64_t r2 = (uint64_t)t2 & MASK51; c = (uint64_t)(t2 >> 51);
  t3 += c;
  uint64_t r3 = (uint64_t)t3 & MASK51; c = (uint64_t)(t3 >> 51);
  t4 += c;
  uint64_t r4 = (uint64_t)t4 & MASK51; c = (uint64_t)(t4 >> 51);
  r0 += 19 * c;
  c = r0 >> 51; r0 &= MASK51; r1 += c;
  o[0] = r0; o[1] = r1; o[2] = r2; o[3] = r3; o[4] = r4;
}

void fe_from_bytes(fe o, const uint8_t s[32]) {
  uint64_t w[4];
  for (int i = 0; i < 4; i++) {
    uint64_t v = 0;
    for (int j = 7; j >= 0; j--) v = (v << 8) | s[8 * i + j];
    w[i] = v;
  }
  o[0] = w[0] & MASK51;
  o[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
  o[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
  o[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
  o[4] = (w[3] >> 12) & MASK51;  // drops bit 255
}

// canonical little-endian serialization
void fe_to_bytes(uint8_t s[32], const fe a) {
  fe t;
  fe_copy(t, a);
  fe_carry(t);
  fe_carry(t);
  // reduce mod p: subtract p if t >= p (twice covers the loose bound)
  for (int rep = 0; rep < 2; rep++) {
    uint64_t borrow = 0;
    fe sub;
    const uint64_t P0 = MASK51 - 18;  // 2^51 - 19
    sub[0] = t[0] - P0 - borrow; borrow = (sub[0] >> 63) & 1; sub[0] &= MASK51;
    for (int i = 1; i < 5; i++) {
      sub[i] = t[i] - MASK51 - borrow;
      borrow = (sub[i] >> 63) & 1;
      sub[i] &= MASK51;
    }
    if (!borrow) fe_copy(t, sub);
  }
  fe_carry(t);  // flatten a possible 2^51 limb before packing
  uint64_t w0 = t[0] | (t[1] << 51);
  uint64_t w1 = (t[1] >> 13) | (t[2] << 38);
  uint64_t w2 = (t[2] >> 26) | (t[3] << 25);
  uint64_t w3 = (t[3] >> 39) | (t[4] << 12);
  uint64_t w[4] = {w0, w1, w2, w3};
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) s[8 * i + j] = uint8_t(w[i] >> (8 * j));
}

void fe_invert(fe o, const fe z) {
  fe t0, t1, t2, t3;
  fe_sq(t0, z);                       // 2
  fe_sq(t1, t0); fe_sq(t1, t1);      // 8
  fe_mul(t1, z, t1);                  // 9
  fe_mul(t0, t0, t1);                 // 11
  fe_sq(t2, t0);                      // 22
  fe_mul(t1, t1, t2);                 // 2^5 - 1
  fe_sq(t2, t1);
  for (int i = 1; i < 5; i++) fe_sq(t2, t2);
  fe_mul(t1, t2, t1);                 // 2^10 - 1
  fe_sq(t2, t1);
  for (int i = 1; i < 10; i++) fe_sq(t2, t2);
  fe_mul(t2, t2, t1);                 // 2^20 - 1
  fe_sq(t3, t2);
  for (int i = 1; i < 20; i++) fe_sq(t3, t3);
  fe_mul(t2, t3, t2);                 // 2^40 - 1
  fe_sq(t2, t2);
  for (int i = 1; i < 10; i++) fe_sq(t2, t2);
  fe_mul(t1, t2, t1);                 // 2^50 - 1
  fe_sq(t2, t1);
  for (int i = 1; i < 50; i++) fe_sq(t2, t2);
  fe_mul(t2, t2, t1);                 // 2^100 - 1
  fe_sq(t3, t2);
  for (int i = 1; i < 100; i++) fe_sq(t3, t3);
  fe_mul(t2, t3, t2);                 // 2^200 - 1
  fe_sq(t2, t2);
  for (int i = 1; i < 50; i++) fe_sq(t2, t2);
  fe_mul(t1, t2, t1);                 // 2^250 - 1
  fe_sq(t1, t1);
  for (int i = 1; i < 5; i++) fe_sq(t1, t1);
  fe_mul(o, t1, t0);                  // 2^255 - 21
}

// z^((p-5)/8) = z^(2^252 - 3)
void fe_pow2523(fe o, const fe z) {
  fe t0, t1, t2;
  fe_sq(t0, z);                       // 2
  fe_sq(t1, t0); fe_sq(t1, t1);      // 8
  fe_mul(t1, z, t1);                  // 9
  fe_mul(t0, t0, t1);                 // 11
  fe_sq(t0, t0);                      // 22
  fe_mul(t0, t1, t0);                 // 2^5 - 1
  fe_sq(t1, t0);
  for (int i = 1; i < 5; i++) fe_sq(t1, t1);
  fe_mul(t0, t1, t0);                 // 2^10 - 1
  fe_sq(t1, t0);
  for (int i = 1; i < 10; i++) fe_sq(t1, t1);
  fe_mul(t1, t1, t0);                 // 2^20 - 1
  fe_sq(t2, t1);
  for (int i = 1; i < 20; i++) fe_sq(t2, t2);
  fe_mul(t1, t2, t1);                 // 2^40 - 1
  fe_sq(t1, t1);
  for (int i = 1; i < 10; i++) fe_sq(t1, t1);
  fe_mul(t0, t1, t0);                 // 2^50 - 1
  fe_sq(t1, t0);
  for (int i = 1; i < 50; i++) fe_sq(t1, t1);
  fe_mul(t1, t1, t0);                 // 2^100 - 1
  fe_sq(t2, t1);
  for (int i = 1; i < 100; i++) fe_sq(t2, t2);
  fe_mul(t1, t2, t1);                 // 2^200 - 1
  fe_sq(t1, t1);
  for (int i = 1; i < 50; i++) fe_sq(t1, t1);
  fe_mul(t0, t1, t0);                 // 2^250 - 1
  fe_sq(t0, t0); fe_sq(t0, t0);      // 2^252 - 4
  fe_mul(o, t0, z);                   // 2^252 - 3
}

int fe_is_zero(const fe a) {
  uint8_t s[32];
  fe_to_bytes(s, a);
  uint8_t acc = 0;
  for (int i = 0; i < 32; i++) acc |= s[i];
  return acc == 0;
}

int fe_parity(const fe a) {
  uint8_t s[32];
  fe_to_bytes(s, a);
  return s[0] & 1;
}

// d = -121665/121666 and sqrt(-1), from the curve definition
const fe FE_D = {929955233495203ULL, 466365720129213ULL, 1662059464998953ULL,
                 2033849074728123ULL, 1442794654840575ULL};
const fe FE_D2 = {1859910466990425ULL, 932731440258426ULL, 1072319116312658ULL,
                  1815898335770999ULL, 633789495995903ULL};
const fe FE_SQRTM1 = {1718705420411056ULL, 234908883556509ULL,
                      2233514472574048ULL, 2117202627021982ULL,
                      765476049583133ULL};

// ---------------------------------------------------------------------------
// group: extended coordinates (X, Y, Z, T), complete formulas
// ---------------------------------------------------------------------------

struct ge {
  fe X, Y, Z, T;
};

void ge_identity(ge* p) {
  fe_zero(p->X);
  fe_one(p->Y);
  fe_one(p->Z);
  fe_zero(p->T);
}

void ge_add(ge* o, const ge* p, const ge* q) {
  fe a, b, c, d, e, f, g, h, t;
  fe_sub(a, p->Y, p->X); fe_sub(t, q->Y, q->X); fe_mul(a, a, t);
  fe_add(b, p->Y, p->X); fe_carry(b);
  fe_add(t, q->Y, q->X); fe_carry(t);
  fe_mul(b, b, t);
  fe_mul(c, p->T, q->T); fe_mul(c, c, FE_D2);
  fe_mul(d, p->Z, q->Z); fe_add(d, d, d); fe_carry(d);
  fe_sub(e, b, a);
  fe_sub(f, d, c);
  fe_add(g, d, c); fe_carry(g);
  fe_add(h, b, a); fe_carry(h);
  fe_mul(o->X, e, f);
  fe_mul(o->Y, g, h);
  fe_mul(o->Z, f, g);
  fe_mul(o->T, e, h);
}

void ge_double(ge* o, const ge* p) {
  fe a, b, c, e, f, g, h, t;
  fe_sq(a, p->X);
  fe_sq(b, p->Y);
  fe_sq(c, p->Z); fe_add(c, c, c); fe_carry(c);
  fe_add(h, a, b); fe_carry(h);
  fe_add(t, p->X, p->Y); fe_carry(t); fe_sq(t, t);
  fe_sub(e, h, t);
  fe_sub(g, a, b);
  fe_add(f, c, g); fe_carry(f);
  fe_mul(o->X, e, f);
  fe_mul(o->Y, g, h);
  fe_mul(o->Z, f, g);
  fe_mul(o->T, e, h);
}

// decompression, staged so the (p-5)/8 power chain — its dominant cost
// — can run 8-wide over independent points (fe_ifma.h):
//   prep (scalar):  parse y, compute u, v, v^3 and t_in = u v^7
//   pow:            t = t_in^((p-5)/8)   [vectorizable]
//   finish (scalar): x = u v^3 t, sqrt check, sign — ALL accept/reject
//                    decisions happen here, identically for both paths
struct DecompPre {
  fe y, u, v, v3, tin;
  int sign;
};

static int decompress_prep(DecompPre* st, const uint8_t s[32]) {
  // reject non-canonical y >= p
  static const uint8_t PBYTES[32] = {
      0xed, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f};
  uint8_t ymasked[32];
  std::memcpy(ymasked, s, 32);
  st->sign = ymasked[31] >> 7;
  ymasked[31] &= 0x7f;
  int ge_p = 1;
  for (int i = 31; i >= 0; i--) {
    if (ymasked[i] < PBYTES[i]) { ge_p = 0; break; }
    if (ymasked[i] > PBYTES[i]) { ge_p = 1; break; }
  }
  if (ge_p) return 0;
  fe y2, one;
  fe_from_bytes(st->y, ymasked);
  fe_sq(y2, st->y);
  fe_one(one);
  fe_sub(st->u, y2, one);                    // y^2 - 1
  fe_mul(st->v, y2, FE_D);
  fe_add(st->v, st->v, one); fe_carry(st->v);  // d y^2 + 1
  fe_sq(st->v3, st->v); fe_mul(st->v3, st->v3, st->v);  // v^3
  fe_sq(st->tin, st->v3); fe_mul(st->tin, st->tin, st->v);  // v^7
  fe_mul(st->tin, st->tin, st->u);           // u v^7
  return 1;
}

static int decompress_finish(ge* p, const DecompPre* st, const fe t_pow) {
  fe x, vx2, chk;
  const fe& u = st->u;
  const fe& v = st->v;
  int sign = st->sign;
  fe_mul(x, u, st->v3); fe_mul(x, x, t_pow);  // u v^3 (u v^7)^((p-5)/8)
  fe_sq(vx2, x); fe_mul(vx2, vx2, v); // v x^2
  fe_sub(chk, vx2, u);
  if (!fe_is_zero(chk)) {
    fe_add(chk, vx2, u); fe_carry(chk);
    if (!fe_is_zero(chk)) return 0;
    fe_mul(x, x, FE_SQRTM1);
  }
  if (fe_is_zero(x) && sign) return 0;  // -0 is invalid
  if (fe_parity(x) != sign) {
    fe zero;
    fe_zero(zero);
    fe_sub(x, zero, x);
  }
  fe_copy(p->X, x);
  fe_copy(p->Y, st->y);
  fe_one(p->Z);
  fe_mul(p->T, x, st->y);
  return 1;
}

// decompress: returns 1 if s is a valid canonical point encoding
int ge_from_bytes(ge* p, const uint8_t s[32]) {
  DecompPre st;
  if (!decompress_prep(&st, s)) return 0;
  fe t;
  fe_pow2523(t, st.tin);
  return decompress_finish(p, &st, t);
}

// batch decompression: out[i] valid iff ok[i]; the power chains run
// eight points at a time through fe8_pow2523 when IFMA is available,
// with bit-identical results to the scalar chain (same additions, same
// radix — only the lane count differs).
void ge_from_bytes_batch(ge* out, uint8_t* ok,
                         const uint8_t* const* encs, size_t n) {
  std::vector<DecompPre> pre(n);
  for (size_t i = 0; i < n; i++) ok[i] = (uint8_t)decompress_prep(&pre[i], encs[i]);
  size_t i = 0;
#ifdef TM_HAVE_FE8
  // groups of 8 prepped points (skip over prep failures)
  size_t idx[8];
  for (;;) {
    size_t g = 0;
    size_t scan = i;
    while (scan < n && g < 8) {
      if (ok[scan]) idx[g++] = scan;
      scan++;
    }
    if (g < 8) break;  // remainder handled scalar below
    uint64_t in[8][5], outp[8][5];
    for (size_t k = 0; k < 8; k++)
      for (int j = 0; j < 5; j++) in[k][j] = pre[idx[k]].tin[j];
    fe8 z, t;
    fe8_load(&z, in);
    fe8_pow2523(&t, &z);
    fe8_store(outp, &t);
    for (size_t k = 0; k < 8; k++) {
      fe tp;
      for (int j = 0; j < 5; j++) tp[j] = outp[k][j];
      ok[idx[k]] = (uint8_t)decompress_finish(&out[idx[k]], &pre[idx[k]], tp);
    }
    i = idx[7] + 1;
  }
#endif
  for (; i < n; i++) {
    if (!ok[i]) continue;
    fe t;
    fe_pow2523(t, pre[i].tin);
    ok[i] = (uint8_t)decompress_finish(&out[i], &pre[i], t);
  }
}

void ge_neg(ge* o, const ge* p) {
  fe zero;
  fe_zero(zero);
  fe_sub(o->X, zero, p->X);
  fe_copy(o->Y, p->Y);
  fe_copy(o->Z, p->Z);
  fe_sub(o->T, zero, p->T);
}

void ge_to_bytes(uint8_t s[32], const ge* p) {
  fe zi, x, y;
  fe_invert(zi, p->Z);
  fe_mul(x, p->X, zi);
  fe_mul(y, p->Y, zi);
  fe_to_bytes(s, y);
  s[31] ^= uint8_t(fe_parity(x) << 7);
}

// base point B
const fe GE_BX = {1738742601995546ULL, 1146398526822698ULL,
                  2070867633025821ULL, 562264141797630ULL,
                  587772402128613ULL};
const fe GE_BY = {1801439850948184ULL, 1351079888211148ULL,
                  450359962737049ULL, 900719925474099ULL,
                  1801439850948198ULL};

// ---------------------------------------------------------------------------
// scalars mod L
// ---------------------------------------------------------------------------

const uint8_t LBYTES[32] = {0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58,
                            0xd6, 0x9c, 0xf7, 0xa2, 0xde, 0xf9, 0xde, 0x14,
                            0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
                            0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10};

// little-endian compare: a >= b
int bytes_ge(const uint8_t* a, const uint8_t* b, int n) {
  for (int i = n - 1; i >= 0; i--) {
    if (a[i] > b[i]) return 1;
    if (a[i] < b[i]) return 0;
  }
  return 1;  // equal
}

// r = x mod L for a 64-byte little-endian x.
//
// Table-based digit sum + one fold: x = sum_i d_i * 2^(16 i) with 16-bit
// digits, so x === sum_i d_i * (2^(16 i) mod L). The column sums fit
// easily in 128 bits (32 digits * 2^16 * 2^64 = 2^85) and normalize to a
// value v < 32 * 65535 * L < 2^273. Then one fold at the 2^252 boundary:
// with L = 2^252 + delta (delta < 2^125), v === lo - hi*delta (mod L)
// where hi = v >> 252 < 2^21, so hi*delta < 2^146 (3 limbs, and
// < L so one conditional add of L after an underflowing subtraction
// restores [0, L)). This is ~500 simple
// word ops vs the 512-iteration bitwise loop it replaces (which
// dominated the batch-marshal profile at ~3us per digest).
namespace {

struct ScTables {
  uint64_t r16[32][4];  // 2^(16 i) mod L, 4 LE 64-bit limbs
  uint64_t l[4];        // L
  uint64_t delta[2];    // L - 2^252 (< 2^125)
  ScTables() {
    for (int i = 0; i < 4; i++) {
      uint64_t v = 0;
      for (int j = 7; j >= 0; j--) v = (v << 8) | LBYTES[8 * i + j];
      l[i] = v;
    }
    delta[0] = l[0];
    delta[1] = l[1];
    // clear the 2^252 bit (L's only set bit at/above limb 2 is bit 252)
    uint64_t t[4] = {1, 0, 0, 0};  // current = 2^(16 i) mod L, start i=0
    for (int i = 0; i < 32; i++) {
      for (int j = 0; j < 4; j++) r16[i][j] = t[j];
      for (int b = 0; b < 16; b++) {
        // t = (2 t) mod L; t < L < 2^253 so 2t fits in 4 limbs
        uint64_t carry = 0;
        for (int j = 0; j < 4; j++) {
          uint64_t nv = (t[j] << 1) | carry;
          carry = t[j] >> 63;
          t[j] = nv;
        }
        int ge = 1;
        for (int j = 3; j >= 0; j--) {
          if (t[j] > l[j]) { ge = 1; break; }
          if (t[j] < l[j]) { ge = 0; break; }
        }
        if (ge) {
          unsigned __int128 borrow = 0;
          for (int j = 0; j < 4; j++) {
            unsigned __int128 d =
                (unsigned __int128)t[j] - l[j] - (uint64_t)borrow;
            t[j] = (uint64_t)d;
            borrow = (d >> 64) & 1;
          }
        }
      }
    }
  }
};

}  // namespace

void sc_reduce64(uint8_t r[32], const uint8_t x[64]) {
  static const ScTables T;  // thread-safe magic-static init
  unsigned __int128 col[4] = {0, 0, 0, 0};
  for (int i = 0; i < 32; i++) {
    uint64_t d = (uint64_t)x[2 * i] | ((uint64_t)x[2 * i + 1] << 8);
    if (!d) continue;
    for (int j = 0; j < 4; j++)
      col[j] += (unsigned __int128)d * T.r16[i][j];
  }
  uint64_t v[5];
  unsigned __int128 carry = 0;
  for (int j = 0; j < 4; j++) {
    carry += col[j];
    v[j] = (uint64_t)carry;
    carry >>= 64;
  }
  v[4] = (uint64_t)carry;
  // fold at 2^252 (252 = 3*64 + 60)
  uint64_t hi = (v[3] >> 60) | (v[4] << 4);
  uint64_t lo[4] = {v[0], v[1], v[2], v[3] & ((1ULL << 60) - 1)};
  unsigned __int128 m0 = (unsigned __int128)hi * T.delta[0];
  unsigned __int128 m1 = (unsigned __int128)hi * T.delta[1];
  unsigned __int128 mid = (m0 >> 64) + (uint64_t)m1;
  uint64_t s[4] = {(uint64_t)m0, (uint64_t)mid,
                   (uint64_t)((mid >> 64) + (uint64_t)(m1 >> 64)), 0};
  unsigned __int128 borrow = 0;
  for (int j = 0; j < 4; j++) {
    unsigned __int128 d = (unsigned __int128)lo[j] - s[j] - (uint64_t)borrow;
    lo[j] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
  if (borrow) {  // negative: one add of L restores [0, L)
    unsigned __int128 c2 = 0;
    for (int j = 0; j < 4; j++) {
      c2 += (unsigned __int128)lo[j] + T.l[j];
      lo[j] = (uint64_t)c2;
      c2 >>= 64;
    }
  }
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) r[8 * i + j] = uint8_t(lo[i] >> (8 * j));
}

// ---------------------------------------------------------------------------
// double-scalar mult: [s]B + [h]A via interleaved 2-bit-window Straus
// ---------------------------------------------------------------------------

void ge_double_scalarmult(ge* out, const uint8_t s[32], const ge* a,
                          const uint8_t h[32]) {
  ge bpt;
  fe_copy(bpt.X, GE_BX);
  fe_copy(bpt.Y, GE_BY);
  fe_one(bpt.Z);
  fe_mul(bpt.T, GE_BX, GE_BY);

  // table[i + 4j] = [i]B + [j]A, i,j in 0..3
  ge table[16];
  ge_identity(&table[0]);
  table[1] = bpt;
  ge_double(&table[2], &bpt);
  ge_add(&table[3], &table[2], &bpt);
  table[4] = *a;
  ge_double(&table[8], a);
  ge_add(&table[12], &table[8], a);
  for (int j = 1; j < 4; j++)
    for (int i = 1; i < 4; i++) ge_add(&table[i + 4 * j], &table[i], &table[4 * j]);

  ge acc;
  ge_identity(&acc);
  for (int k = 127; k >= 0; k--) {
    ge_double(&acc, &acc);
    ge_double(&acc, &acc);
    int sb = (s[(2 * k) / 8] >> ((2 * k) % 8)) & 1;
    int sb1 = (2 * k + 1 < 256) ? (s[(2 * k + 1) / 8] >> ((2 * k + 1) % 8)) & 1 : 0;
    int hb = (h[(2 * k) / 8] >> ((2 * k) % 8)) & 1;
    int hb1 = (2 * k + 1 < 256) ? (h[(2 * k + 1) / 8] >> ((2 * k + 1) % 8)) & 1 : 0;
    int idx = (sb | (sb1 << 1)) + 4 * (hb | (hb1 << 1));
    if (idx) ge_add(&acc, &acc, &table[idx]);
  }
  *out = acc;
}

}  // namespace

// ---------------------------------------------------------------------------
// batch verification: random linear combination (cofactorless)
// ---------------------------------------------------------------------------
//
// Accepts a batch iff  sum_i z_i * ([s_i]B - R_i - [h_i]A_i) == identity
// for fresh random 128-bit z_i — the standard Ed25519 batch-verification
// argument (dalek's verify_batch, BGLS-style): if any single term is a
// nonzero group element, the z-weighted sum is nonzero except with
// probability 2^-128. The per-signature pre-checks (s < L; R and A must
// decode via ge_from_bytes, which accepts ONLY canonical encodings, so
// group equality of [s]B - [h]A and R is equivalent to ed25519_verify's
// canonical byte compare) make the accept set identical to the strict
// per-item loop's, up to that 2^-128 soundness bound. The caller treats
// a 0 return as "some signature bad OR undecided" and falls back to the
// exact per-item loop for lane verdicts.
//
// Cost: one Pippenger multi-scalar multiplication over 2n+1 points
// (window c, ~(256/c)*(2n + 2^c) additions) + n R-decompressions +
// cached A-decompressions — ~3-4x fewer field ops than n independent
// Straus ladders at n >= a few hundred.

namespace {

// r = (a + b) mod L; inputs < L
void sc_add_mod_l(uint8_t r[32], const uint8_t a[32], const uint8_t b[32]) {
  uint8_t t[32];
  unsigned carry = 0;
  for (int i = 0; i < 32; i++) {
    unsigned s = (unsigned)a[i] + b[i] + carry;
    t[i] = uint8_t(s);
    carry = s >> 8;
  }
  if (bytes_ge(t, LBYTES, 32)) {
    unsigned borrow = 0;
    for (int i = 0; i < 32; i++) {
      int d = (int)t[i] - LBYTES[i] - (int)borrow;
      borrow = d < 0;
      r[i] = uint8_t(d + (borrow ? 256 : 0));
    }
  } else {
    std::memcpy(r, t, 32);
  }
}

// r = (a * b) mod L via 4x4 64-bit schoolbook + sc_reduce64
void sc_mul_mod_l(uint8_t r[32], const uint8_t a[32], const uint8_t b[32]) {
  uint64_t al[4], bl[4];
  for (int i = 0; i < 4; i++) {
    uint64_t va = 0, vb = 0;
    for (int j = 7; j >= 0; j--) {
      va = (va << 8) | a[8 * i + j];
      vb = (vb << 8) | b[8 * i + j];
    }
    al[i] = va;
    bl[i] = vb;
  }
  uint64_t res[8] = {0};
  for (int i = 0; i < 4; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 4; j++) {
      carry += (unsigned __int128)al[i] * bl[j] + res[i + j];
      res[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    res[i + 4] = (uint64_t)carry;
  }
  uint8_t wide[64];
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 8; j++) wide[8 * i + j] = uint8_t(res[i] >> (8 * j));
  sc_reduce64(r, wide);
}

// Fill buf with OS randomness. The z_i MUST be independent fresh
// 128-bit values — predictable z lets an attacker balance two invalid
// signatures against each other inside the combined equation, and the
// accepting fast path never consults the per-item loop. So: no PRG (a
// 64-bit-seeded generator would cap soundness at 2^-64), and failure to
// read means the CALLER MUST REFUSE the fast path, not degrade.
bool os_random(uint8_t* buf, size_t len) {
  FILE* f = std::fopen("/dev/urandom", "rb");
  if (!f) return false;
  size_t got = std::fread(buf, 1, len, f);
  std::fclose(f);
  return got == len;
}

// open-addressing cache of decompressed (negated) pubkeys, FNV-1a keyed;
// replaces unordered_map (header conflict above). Capacity is 2x the
// batch's worst case, so probes terminate.
struct NegACache {
  std::vector<std::array<uint8_t, 32>> keys;
  std::vector<ge> vals;
  std::vector<uint8_t> used;
  size_t mask;
  explicit NegACache(size_t n) {
    size_t cap = 16;
    while (cap < 2 * n) cap <<= 1;
    keys.resize(cap);
    vals.resize(cap);
    used.assign(cap, 0);
    mask = cap - 1;
  }
  static size_t hash(const uint8_t* k) {
    uint64_t h = 1469598103934665603ULL;
    for (int i = 0; i < 32; i++) h = (h ^ k[i]) * 1099511628211ULL;
    return (size_t)h;
  }
  // returns the slot; *found tells whether vals[slot] is valid
  size_t slot_for(const uint8_t* k, bool* found) const {
    size_t i = hash(k) & mask;
    while (used[i]) {
      if (std::memcmp(keys[i].data(), k, 32) == 0) {
        *found = true;
        return i;
      }
      i = (i + 1) & mask;
    }
    *found = false;
    return i;
  }
  void put(size_t slot, const uint8_t* k, const ge& v) {
    std::memcpy(keys[slot].data(), k, 32);
    vals[slot] = v;
    used[slot] = 1;
  }
};

// affine "niels" form (y+x, y-x, 2dxy) for the bucket loop: a mixed
// add/sub against an affine point is 7 fe_mul vs ge_add's 9.
struct ge_niels {
  fe yplusx, yminusx, xy2d;
};

// o = p + q, q affine in niels form (ref10-style madd, complete)
void ge_madd(ge* o, const ge* p, const ge_niels* q) {
  fe a, b, c, d, e, f, g, h;
  fe_sub(a, p->Y, p->X); fe_mul(a, a, q->yminusx);
  fe_add(b, p->Y, p->X); fe_carry(b); fe_mul(b, b, q->yplusx);
  fe_mul(c, p->T, q->xy2d);
  fe_add(d, p->Z, p->Z); fe_carry(d);
  fe_sub(e, b, a);
  fe_sub(f, d, c);
  fe_add(g, d, c); fe_carry(g);
  fe_add(h, b, a); fe_carry(h);
  fe_mul(o->X, e, f);
  fe_mul(o->Y, g, h);
  fe_mul(o->Z, f, g);
  fe_mul(o->T, e, h);
}

// o = p - q: -q swaps (y+x, y-x) and negates 2dxy, so C changes sign
void ge_msub(ge* o, const ge* p, const ge_niels* q) {
  fe a, b, c, d, e, f, g, h;
  fe_sub(a, p->Y, p->X); fe_mul(a, a, q->yplusx);
  fe_add(b, p->Y, p->X); fe_carry(b); fe_mul(b, b, q->yminusx);
  fe_mul(c, p->T, q->xy2d);
  fe_add(d, p->Z, p->Z); fe_carry(d);
  fe_sub(e, b, a);
  fe_add(f, d, c); fe_carry(f);
  fe_sub(g, d, c);
  fe_add(h, b, a); fe_carry(h);
  fe_mul(o->X, e, f);
  fe_mul(o->Y, g, h);
  fe_mul(o->Z, f, g);
  fe_mul(o->T, e, h);
}

inline int fe_is_one_limbs(const fe a) {
  return a[0] == 1 && !a[1] && !a[2] && !a[3] && !a[4];
}

// signed c-bit digit recoding: d_w in [-(2^(c-1)-1), 2^(c-1)], so point
// negation (free in Edwards) halves the bucket count vs unsigned digits.
static void recode_signed(const std::array<uint8_t, 32>& s, int c, int nwin,
                          int16_t* out) {
  uint32_t carry = 0;
  uint32_t half = 1u << (c - 1);
  for (int w = 0; w < nwin; w++) {
    int bit0 = w * c;
    uint32_t v = carry;
    for (int k = 0; k < c; k++) {
      int bit = bit0 + k;
      if (bit < 256) v += uint32_t((s[bit >> 3] >> (bit & 7)) & 1u) << k;
    }
    if (v > half) {
      out[w] = (int16_t)((int32_t)v - (1 << c));
      carry = 1;
    } else {
      out[w] = (int16_t)v;
      carry = 0;
    }
  }
}

// test seam: 0 = auto (vectorized when wide + IFMA), 1 = force scalar,
// 2 = force vectorized — differential tests drive both paths via
// tm_ed25519_msm_path (api.cc)
int g_msm_path = 0;

// Pippenger bucket MSM with signed digits and mixed (affine-niels)
// bucket additions. The RLC caller's points are all fresh
// decompressions (Z == 1); a non-affine input is normalized first.
void msm(ge* out, const std::vector<std::array<uint8_t, 32>>& scalars,
         const std::vector<ge>& pts) {
  size_t m = pts.size();
  // half the scalars (the R coefficients z_i) are only 128-bit; they
  // drop out of the upper windows, which the window-size model must see
  size_t n_short = 0;
  for (const auto& s : scalars) {
    int short_ = 1;
    for (int j = 17; j < 32; j++)
      if (s[j]) { short_ = 0; break; }
    n_short += short_;
  }
  // choose c minimizing fe_mul count: madd = 7, ge_add = 9; long
  // scalars hit every window, short ones only the low ceil(136/c)
  int c = 4;
  double best_cost = 1e30;
  for (int cand = 4; cand <= 15; cand++) {
    int nwin = (256 + cand) / cand + 1;
    int nwin_short = (136 + cand - 1) / cand;
    if (nwin_short > nwin) nwin_short = nwin;
    double cost = 7.0 * ((double)(m - n_short) * nwin +
                         (double)n_short * nwin_short) +
                  9.0 * 2.0 * ((double)nwin * ((1u << (cand - 1)) - 1));
    if (cost < best_cost) {
      best_cost = cost;
      c = cand;
    }
  }
  int nwin = (256 + c) / c + 1;  // room for the recoding carry
  size_t nb = (size_t)1 << (c - 1);

  // niels form of every (affine) point
  std::vector<ge_niels> nls(m);
  for (size_t i = 0; i < m; i++) {
    ge p = pts[i];
    if (!fe_is_one_limbs(p.Z)) {  // general-caller fallback: normalize
      fe zi;
      fe_invert(zi, p.Z);
      fe_mul(p.X, p.X, zi);
      fe_mul(p.Y, p.Y, zi);
      fe_one(p.Z);
      fe_mul(p.T, p.X, p.Y);
    }
    fe_add(nls[i].yplusx, p.Y, p.X); fe_carry(nls[i].yplusx);
    // carried: the vectorized bucket path broadcasts these limbs into
    // vpmadd52 operands, which truncate at 52 bits — a loose fe_sub
    // result would silently lose its 53rd bit there
    fe_sub(nls[i].yminusx, p.Y, p.X); fe_carry(nls[i].yminusx);
    fe_mul(nls[i].xy2d, p.T, FE_D2);
  }

  // recode with a window stride padded to a multiple of 8 so the
  // vectorized path can always read 8 digits per point-group
  int ngroups = (nwin + 7) / 8;
  int nwinp = ngroups * 8;
  std::vector<int16_t> digits((size_t)nwinp * m, 0);
  std::vector<int16_t> maxw(m, -1);  // highest nonzero window per point
  int top = 0;
  for (size_t i = 0; i < m; i++) {
    recode_signed(scalars[i], c, nwin, &digits[(size_t)nwinp * i]);
    for (int w = nwin - 1; w >= 0; w--)
      if (digits[(size_t)nwinp * i + w]) { maxw[i] = (int16_t)w; break; }
    if (maxw[i] > top) top = maxw[i];
  }

#ifdef TM_HAVE_FE8
  if (g_msm_path != 1 && (m >= 128 || g_msm_path == 2)) {
    // one window-group (8 windows' bucket arrays) at a time: per point,
    // gather the 8 target buckets, one shared-niels signed mixed add
    // across lanes, masked scatter back. Short scalars (maxw below the
    // group) skip whole groups.
    std::vector<ge> S(nwin);
    std::vector<ge> buckets8((size_t)8 * nb);
    fe8 d2b;
    fe8_broadcast(&d2b, FE_D2);
    for (int g2 = 0; g2 < ngroups; g2++) {
      int w0 = 8 * g2;
      if (w0 > top) {
        for (int l = 0; l < 8 && w0 + l < nwin; l++) ge_identity(&S[w0 + l]);
        continue;
      }
      for (auto& b : buckets8) ge_identity(&b);
      for (size_t i = 0; i < m; i++) {
        if (maxw[i] < w0) continue;
        const int16_t* dp = &digits[(size_t)nwinp * i + w0];
        alignas(64) uint64_t off_arr[8];
        __mmask8 act = 0, neg = 0;
        for (int l = 0; l < 8; l++) {
          int d = dp[l];
          if (d) act |= (__mmask8)(1u << l);
          if (d < 0) { neg |= (__mmask8)(1u << l); d = -d; }
          size_t idx = d ? (size_t)(d - 1) : 0;
          off_arr[l] = ((size_t)l * nb + idx) * sizeof(ge);
        }
        if (!act) continue;
        __m512i off = _mm512_load_si512((const void*)off_arr);
        ge8 cur, res;
        ge8_gather(&cur, buckets8.data(), off);
        fe8 ypx, ymx, x2d;
        fe8_broadcast(&ypx, nls[i].yplusx);
        fe8_broadcast(&ymx, nls[i].yminusx);
        fe8_broadcast(&x2d, nls[i].xy2d);
        ge8_madd_signed(&res, &cur, &ypx, &ymx, &x2d, neg);
        ge8_mask_scatter(buckets8.data(), act, off, &res);
      }
      // suffix-sum aggregation, all 8 windows of the group in lanes
      ge8 running, sum;
      {
        ge id;
        ge_identity(&id);
        fe8_broadcast(&running.X, id.X);
        fe8_broadcast(&running.Y, id.Y);
        fe8_broadcast(&running.Z, id.Z);
        fe8_broadcast(&running.T, id.T);
        sum = running;
      }
      alignas(64) uint64_t lane_base[8];
      for (int l = 0; l < 8; l++)
        lane_base[l] = (size_t)l * nb * sizeof(ge);
      __m512i base_off = _mm512_load_si512((const void*)lane_base);
      for (size_t d = nb; d >= 1; d--) {
        ge8 bkt;
        __m512i off = _mm512_add_epi64(
            base_off, _mm512_set1_epi64((long long)((d - 1) * sizeof(ge))));
        ge8_gather(&bkt, buckets8.data(), off);
        ge8_add(&running, &running, &bkt, &d2b);
        ge8_add(&sum, &sum, &running, &d2b);
      }
      // extract the 8 per-window sums
      alignas(64) uint64_t s_off[8];
      int live = (nwin - w0 < 8) ? (nwin - w0) : 8;
      ge spill[8];
      for (int l = 0; l < 8; l++) s_off[l] = (size_t)l * sizeof(ge);
      ge8_mask_scatter(spill, (__mmask8)0xFF, _mm512_load_si512((const void*)s_off),
                       &sum);
      for (int l = 0; l < live; l++) S[w0 + l] = spill[l];
    }
    // Horner combine from the top window down
    ge acc;
    ge_identity(&acc);
    for (int w = top; w >= 0; w--) {
      if (w != top)
        for (int k = 0; k < c; k++) ge_double(&acc, &acc);
      ge_add(&acc, &acc, &S[w]);
    }
    *out = acc;
    return;
  }
#endif

  std::vector<ge> buckets(nb);
  ge acc;
  ge_identity(&acc);
  for (int w = top; w >= 0; w--) {
    if (w != top)
      for (int k = 0; k < c; k++) ge_double(&acc, &acc);
    for (auto& b : buckets) ge_identity(&b);
    for (size_t i = 0; i < m; i++) {
      int d = digits[(size_t)nwinp * i + w];
      if (d > 0) ge_madd(&buckets[d - 1], &buckets[d - 1], &nls[i]);
      else if (d < 0) ge_msub(&buckets[-d - 1], &buckets[-d - 1], &nls[i]);
    }
    // sum_d d * bucket[d] via suffix sums
    ge running, sum;
    ge_identity(&running);
    ge_identity(&sum);
    for (size_t d = nb; d >= 1; d--) {
      ge_add(&running, &running, &buckets[d - 1]);
      ge_add(&sum, &sum, &running);
    }
    ge_add(&acc, &acc, &sum);
  }
  *out = acc;
}

}  // namespace

// defined with the per-item verification family below
static int cheap_sig_checks(const uint8_t sig[64]);
static void collect_unique_a(const uint8_t* pubs, int64_t n,
                             const uint8_t* lane_live, NegACache& cache,
                             std::vector<size_t>& a_slot,
                             std::vector<size_t>& uniq_slots,
                             std::vector<const uint8_t*>& encs);
static void backfill_neg_a(NegACache& cache,
                           const std::vector<size_t>& uniq_slots,
                           const ge* dec, const uint8_t* dec_ok,
                           std::vector<uint8_t>& slot_ok);

int ed25519_verify_batch_rlc(const uint8_t* pubs, const uint8_t* sigs,
                             const uint8_t* msgs, const uint64_t* offsets,
                             int64_t n) {
  if (n <= 0) return 1;
  std::vector<ge> pts;
  std::vector<std::array<uint8_t, 32>> scs;
  pts.reserve(2 * (size_t)n + 1);
  scs.reserve(2 * (size_t)n + 1);
  // one fresh 128-bit z per signature, straight from the OS — if the
  // randomness is unavailable, refuse the fast path (0 sends the caller
  // to the exact per-item loop; see os_random above)
  std::vector<uint8_t> zbuf(16 * (size_t)n);
  if (!os_random(zbuf.data(), zbuf.size())) return 0;
  // cheap byte-range rejects before ANY curve work (the per-item floor
  // does the same, so malformed floods never reach a power chain)
  for (int64_t i = 0; i < n; i++)
    if (!cheap_sig_checks(sigs + 64 * i)) return 0;
  // decompression targets — every R plus each unique A (validator keys
  // repeat across a commit) — collect into ONE batch call so the 8-wide
  // power-chain groups stay full even for tiny commits
  NegACache neg_a_cache((size_t)n);
  std::vector<const uint8_t*> encs((size_t)n);
  for (int64_t i = 0; i < n; i++) encs[i] = sigs + 64 * i;  // R_i
  std::vector<size_t> a_slot, uniq_slots;
  collect_unique_a(pubs, n, nullptr, neg_a_cache, a_slot, uniq_slots, encs);
  std::vector<ge> dec(encs.size());
  std::vector<uint8_t> dec_ok(encs.size());
  ge_from_bytes_batch(dec.data(), dec_ok.data(), encs.data(), encs.size());
  for (int64_t i = 0; i < n; i++)
    if (!dec_ok[i]) return 0;  // invalid R
  std::vector<uint8_t> slot_ok;
  backfill_neg_a(neg_a_cache, uniq_slots, dec.data() + n, dec_ok.data() + n,
                 slot_ok);
  for (int64_t i = 0; i < n; i++)
    if (!slot_ok[a_slot[i]]) return 0;  // invalid A
  uint8_t zsum_s[32] = {0};
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* sig = sigs + 64 * i;
    const uint8_t* pub = pubs + 32 * i;
    const ge& neg_a = neg_a_cache.vals[a_slot[i]];
    uint8_t z[32] = {0};
    std::memcpy(z, zbuf.data() + 16 * i, 16);
    uint8_t z_acc = 0;
    for (int j = 0; j < 16; j++) z_acc |= z[j];
    if (!z_acc) z[0] = 1;  // z must be nonzero
    uint8_t h[32];
    ed25519_hram(sig, pub, msgs + offsets[i], offsets[i + 1] - offsets[i], h);
    uint8_t zs[32], zh[32];
    sc_mul_mod_l(zs, z, sig + 32);
    sc_add_mod_l(zsum_s, zsum_s, zs);
    sc_mul_mod_l(zh, z, h);
    ge nr;
    ge_neg(&nr, &dec[i]);
    std::array<uint8_t, 32> za{}, zha{};
    std::memcpy(za.data(), z, 32);
    std::memcpy(zha.data(), zh, 32);
    pts.push_back(nr);
    scs.push_back(za);
    pts.push_back(neg_a);
    scs.push_back(zha);
  }
  ge b;
  fe_copy(b.X, GE_BX);
  fe_copy(b.Y, GE_BY);
  fe_one(b.Z);
  fe_mul(b.T, GE_BX, GE_BY);
  std::array<uint8_t, 32> sb{};
  std::memcpy(sb.data(), zsum_s, 32);
  pts.push_back(b);
  scs.push_back(sb);
  ge res;
  msm(&res, scs, pts);
  // identity test in projective coords: X == 0 AND Y == Z. The only
  // other point with X == 0 is (0, -1) (order 2), for which Y - Z != 0.
  fe d;
  fe_sub(d, res.Y, res.Z);
  return fe_is_zero(res.X) && fe_is_zero(d);
}

// ---------------------------------------------------------------------------
// public API
// ---------------------------------------------------------------------------

void ed25519_hram(const uint8_t r[32], const uint8_t pub[32],
                  const uint8_t* msg, uint64_t msg_len, uint8_t h_out[32]) {
  Sha512Ctx c;
  sha512_init(&c);
  sha512_update(&c, r, 32);
  sha512_update(&c, pub, 32);
  sha512_update(&c, msg, msg_len);
  uint8_t digest[64];
  sha512_final(&c, digest);
  sc_reduce64(h_out, digest);
}

void ed25519_set_msm_path(int path) { g_msm_path = path; }

void ed25519_decompress_batch(const uint8_t* pubs, int64_t n,
                              uint8_t* xy_out, uint8_t* ok) {
  if (n <= 0) return;
  std::vector<ge> dec((size_t)n);
  std::vector<const uint8_t*> encs((size_t)n);
  for (int64_t i = 0; i < n; i++) encs[i] = pubs + 32 * i;
  ge_from_bytes_batch(dec.data(), ok, encs.data(), (size_t)n);
  for (int64_t i = 0; i < n; i++) {
    if (!ok[i]) continue;
    fe_to_bytes(xy_out + 64 * i, dec[i].X);
    fe_to_bytes(xy_out + 64 * i + 32, dec[i].Y);
  }
}

int ed25519_decompress(const uint8_t pub[32], uint8_t x_out[32],
                       uint8_t y_out[32]) {
  ge a;
  if (!ge_from_bytes(&a, pub)) return 0;
  fe_to_bytes(x_out, a.X);
  fe_to_bytes(y_out, a.Y);
  return 1;
}

// byte-range rejects that need no curve arithmetic: s < L (strict
// RFC 8032) and canonical R.y (matches crypto/ed25519.verify). These
// run BEFORE any decompression on every path, so a flood of malformed
// signatures costs two 32-byte compares per lane, never a power chain.
static int cheap_sig_checks(const uint8_t sig[64]) {
  if (bytes_ge(sig + 32, LBYTES, 32)) return 0;  // s >= L
  uint8_t rm[32];
  std::memcpy(rm, sig, 32);
  rm[31] &= 0x7f;
  static const uint8_t PB[32] = {
      0xed, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f};
  return !bytes_ge(rm, PB, 32);  // non-canonical R.y
}

// Unique-pubkey dedup bookkeeping, shared by the RLC verifier and the
// per-item batch: append each first-seen pubkey among the live lanes to
// `encs` (so the CALLER can pack them into one ge_from_bytes_batch call
// alongside any other points — the RLC path adds all its R points to
// the same call, keeping the 8-wide groups full even for tiny commits)
// and record lane -> cache slot. backfill_neg_a then consumes the
// decompression results for the appended range.
static void collect_unique_a(const uint8_t* pubs, int64_t n,
                             const uint8_t* lane_live, NegACache& cache,
                             std::vector<size_t>& a_slot,
                             std::vector<size_t>& uniq_slots,
                             std::vector<const uint8_t*>& encs) {
  a_slot.assign((size_t)n, 0);
  ge placeholder;
  ge_identity(&placeholder);
  for (int64_t i = 0; i < n; i++) {
    if (lane_live && !lane_live[i]) continue;
    const uint8_t* pub = pubs + 32 * i;
    bool found;
    size_t slot = cache.slot_for(pub, &found);
    if (!found) {
      cache.put(slot, pub, placeholder);  // filled by backfill_neg_a
      uniq_slots.push_back(slot);
      encs.push_back(pub);
    }
    a_slot[i] = slot;
  }
}

// dec/dec_ok point at the decompression results for collect_unique_a's
// appended range (in order); negates each valid key into the cache and
// records per-slot validity.
static void backfill_neg_a(NegACache& cache,
                           const std::vector<size_t>& uniq_slots,
                           const ge* dec, const uint8_t* dec_ok,
                           std::vector<uint8_t>& slot_ok) {
  slot_ok.assign(cache.vals.size(), 0);
  for (size_t k = 0; k < uniq_slots.size(); k++) {
    slot_ok[uniq_slots[k]] = dec_ok[k];
    if (dec_ok[k]) ge_neg(&cache.vals[uniq_slots[k]], &dec[k]);
  }
}

#ifdef TM_HAVE_FE8
// ---------------------------------------------------------------------------
// 8-wide per-item verification (AVX-512 IFMA)
// ---------------------------------------------------------------------------
//
// Eight independent [s]B + [h](-A) Straus ladders in lock-step: limb j
// of eight field elements shares one zmm register, so the 2-bit-window
// ladder's 256 doublings + 128 table adds run once for all eight lanes
// (ge8_dbl/ge8_add mirror the scalar ge_double/ge_add formulas
// exactly). Each lane keeps its own 16-entry [i]B + [j](-A_l) table,
// stored lane-major in one array so the per-window pick is a single
// ge8_gather at per-lane byte offsets; a zero window index gathers the
// identity and adds it unconditionally (the unified a=-1 extended add
// is complete, so this equals the scalar path's skip). Verdicts are the
// scalar path's canonical 32-byte compare per lane. This is the
// exact-verdict floor under the RLC bisection — the adversarial
// dense-flood path — so its constant factor bounds flood cost; measured
// ~6x the scalar ladder at 4096 lanes.

int g_items8_path = 0;  // 0 auto, 1 force scalar, 2 force 8-wide

static void ge8_broadcast_pt(ge8* o, const ge& p) {
  fe8_broadcast(&o->X, p.X);
  fe8_broadcast(&o->Y, p.Y);
  fe8_broadcast(&o->Z, p.Z);
  fe8_broadcast(&o->T, p.T);
}

static void verify8_with_neg_a(const ge* const* neg_a,
                               const uint8_t* const* pub,
                               const uint8_t* const* msg,
                               const uint64_t* msg_len,
                               const uint8_t* const* sig,
                               uint8_t* ok_out) {
  uint8_t h[8][32];
  for (int l = 0; l < 8; l++)
    ed25519_hram(sig[l], pub[l], msg[l], msg_len[l], h[l]);

  fe8 d2b;
  fe8_broadcast(&d2b, FE_D2);

  alignas(64) int64_t lane_off[8];
  for (int l = 0; l < 8; l++) lane_off[l] = (int64_t)(l * sizeof(ge));
  __m512i off_lane = _mm512_load_si512((const void*)lane_off);

  // B multiples are lane-uniform; A multiples lane-vary
  ge bpt, b2, b3, id;
  fe_copy(bpt.X, GE_BX);
  fe_copy(bpt.Y, GE_BY);
  fe_one(bpt.Z);
  fe_mul(bpt.T, GE_BX, GE_BY);
  ge_double(&b2, &bpt);
  ge_add(&b3, &b2, &bpt);
  ge_identity(&id);

  alignas(64) ge a_scratch[8];
  for (int l = 0; l < 8; l++) a_scratch[l] = *neg_a[l];
  ge8 a1, a2, a3;
  ge8_gather(&a1, a_scratch, off_lane);
  ge8_dbl(&a2, &a1);
  ge8_add(&a3, &a2, &a1, &d2b);

  // lane-major table: entry idx = i + 4j holds [i]B + [j](-A_l), lane l
  // of entry idx at table[idx * 8 + l]
  alignas(64) ge table[16 * 8];
  ge8 brow[4], e;
  ge8_broadcast_pt(&brow[0], id);
  ge8_broadcast_pt(&brow[1], bpt);
  ge8_broadcast_pt(&brow[2], b2);
  ge8_broadcast_pt(&brow[3], b3);
  const ge8* arow[4] = {nullptr, &a1, &a2, &a3};
  for (int j = 0; j < 4; j++) {
    for (int i = 0; i < 4; i++) {
      __m512i off = _mm512_add_epi64(
          off_lane,
          _mm512_set1_epi64((long long)((i + 4 * j) * 8 * sizeof(ge))));
      if (j == 0) {
        ge8_mask_scatter(table, (__mmask8)0xFF, off, &brow[i]);
      } else if (i == 0) {
        ge8_mask_scatter(table, (__mmask8)0xFF, off, arow[j]);
      } else {
        ge8_add(&e, arow[j], &brow[i], &d2b);
        ge8_mask_scatter(table, (__mmask8)0xFF, off, &e);
      }
    }
  }

  ge8 acc, cur;
  ge8_broadcast_pt(&acc, id);
  for (int k = 127; k >= 0; k--) {
    ge8_dbl(&acc, &acc);
    ge8_dbl(&acc, &acc);
    alignas(64) int64_t offs[8];
    for (int l = 0; l < 8; l++) {
      const uint8_t* s = sig[l] + 32;
      int sb = (s[(2 * k) / 8] >> ((2 * k) % 8)) & 1;
      int sb1 = (s[(2 * k + 1) / 8] >> ((2 * k + 1) % 8)) & 1;
      int hb = (h[l][(2 * k) / 8] >> ((2 * k) % 8)) & 1;
      int hb1 = (h[l][(2 * k + 1) / 8] >> ((2 * k + 1) % 8)) & 1;
      int idx = (sb | (sb1 << 1)) + 4 * (hb | (hb1 << 1));
      offs[l] = (int64_t)(((size_t)idx * 8 + (size_t)l) * sizeof(ge));
    }
    ge8_gather(&cur, table, _mm512_load_si512((const void*)offs));
    ge8_add(&acc, &acc, &cur, &d2b);
  }

  alignas(64) ge res[8];
  ge8_mask_scatter(res, (__mmask8)0xFF, off_lane, &acc);
  for (int l = 0; l < 8; l++) {
    uint8_t enc[32];
    ge_to_bytes(enc, &res[l]);
    ok_out[l] = (uint8_t)(std::memcmp(enc, sig[l], 32) == 0);
  }
}
#else
int g_items8_path = 0;
#endif  // TM_HAVE_FE8

void ed25519_set_items8_path(int path) { g_items8_path = path; }

// shared tail of single and batch per-item verification: everything
// after the cheap checks pass and A is decompressed and negated
static int verify_with_neg_a(const ge* neg_a, const uint8_t* pub,
                             const uint8_t* msg, uint64_t msg_len,
                             const uint8_t sig[64]) {
  uint8_t h[32];
  ed25519_hram(sig, pub, msg, msg_len, h);
  ge p;
  ge_double_scalarmult(&p, sig + 32, neg_a, h);  // [s]B + [h](-A)
  uint8_t out[32];
  ge_to_bytes(out, &p);
  return std::memcmp(out, sig, 32) == 0;
}

int ed25519_verify(const uint8_t pub[32], const uint8_t* msg, uint64_t msg_len,
                   const uint8_t sig[64]) {
  if (!cheap_sig_checks(sig)) return 0;
  ge a;
  if (!ge_from_bytes(&a, pub)) return 0;
  ge neg_a;
  ge_neg(&neg_a, &a);
  return verify_with_neg_a(&neg_a, pub, msg, msg_len, sig);
}

// per-item verdicts for a whole batch: identical lane semantics to n
// ed25519_verify calls, but the A decompressions dedupe across repeated
// validator keys and run 8-wide (ge_from_bytes_batch). This is the
// exact-verdict floor under the RLC bisection — i.e. the adversarial
// dense-flood path — so its constant factor bounds flood cost; lanes
// failing the byte-range checks never contribute curve work at all.
void ed25519_verify_batch_items(const uint8_t* pubs, const uint8_t* sigs,
                                const uint8_t* msgs, const uint64_t* offsets,
                                int64_t n, uint8_t* out) {
  if (n <= 0) return;
  std::vector<uint8_t> live((size_t)n);
  for (int64_t i = 0; i < n; i++) {
    live[i] = (uint8_t)cheap_sig_checks(sigs + 64 * i);
    out[i] = 0;
  }
  NegACache cache((size_t)n);
  std::vector<const uint8_t*> encs;
  std::vector<size_t> a_slot, uniq_slots;
  collect_unique_a(pubs, n, live.data(), cache, a_slot, uniq_slots, encs);
  std::vector<ge> dec(encs.size());
  std::vector<uint8_t> dec_ok(encs.size());
  if (!encs.empty())
    ge_from_bytes_batch(dec.data(), dec_ok.data(), encs.data(), encs.size());
  std::vector<uint8_t> slot_ok;
  backfill_neg_a(cache, uniq_slots, dec.data(), dec_ok.data(), slot_ok);
#ifdef TM_HAVE_FE8
  if (g_items8_path != 1) {
    // pack live+decodable lanes eight at a time through the IFMA
    // lock-step ladder; the ragged tail runs scalar
    const ge* na[8];
    const uint8_t* pu[8];
    const uint8_t* ms[8];
    uint64_t ml[8];
    const uint8_t* sg[8];
    int64_t lane[8];
    size_t g = 0;
    for (int64_t i = 0; i < n; i++) {
      if (!live[i] || !slot_ok[a_slot[i]]) continue;  // verdict stays 0
      na[g] = &cache.vals[a_slot[i]];
      pu[g] = pubs + 32 * i;
      ms[g] = msgs + offsets[i];
      ml[g] = offsets[i + 1] - offsets[i];
      sg[g] = sigs + 64 * i;
      lane[g] = i;
      if (++g == 8) {
        uint8_t okv[8];
        verify8_with_neg_a(na, pu, ms, ml, sg, okv);
        for (int l = 0; l < 8; l++) out[lane[l]] = okv[l];
        g = 0;
      }
    }
    for (size_t l = 0; l < g; l++)
      out[lane[l]] = (uint8_t)verify_with_neg_a(na[l], pu[l], ms[l], ml[l],
                                                sg[l]);
    return;
  }
#endif
  for (int64_t i = 0; i < n; i++) {
    if (!live[i] || !slot_ok[a_slot[i]]) continue;  // verdict stays 0
    out[i] = (uint8_t)verify_with_neg_a(
        &cache.vals[a_slot[i]], pubs + 32 * i, msgs + offsets[i],
        offsets[i + 1] - offsets[i], sigs + 64 * i);
  }
}

}  // namespace tm
