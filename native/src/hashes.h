// Spec implementations of SHA-256 (FIPS 180-4), SHA-512 (FIPS 180-4) and
// RIPEMD-160 (Dobbertin/Bosselaers/Preneel) for the host data plane.
// These back the CPU fallbacks of the hashing gateway and the ed25519
// batch verifier's inner H(R||A||M).
#pragma once
#include <cstddef>
#include <cstdint>

namespace tm {

void sha256(const uint8_t* data, size_t len, uint8_t out[32]);
void sha512(const uint8_t* data, size_t len, uint8_t out[64]);
void ripemd160(const uint8_t* data, size_t len, uint8_t out[20]);

#if defined(__AVX512F__)
// 16 equal-length messages hashed in lockstep (one uint32 lane each);
// out is lane-major, 16*20 bytes. Bit-identical to 16 scalar calls.
void ripemd160_x16(const uint8_t* const msgs[16], size_t len, uint8_t* out);
#endif

// streaming sha512 for H(R || A || M) without concatenation copies
struct Sha512Ctx {
  uint64_t h[8];
  uint8_t buf[128];
  uint64_t total;
  size_t buflen;
};
void sha512_init(Sha512Ctx* c);
void sha512_update(Sha512Ctx* c, const uint8_t* data, size_t len);
void sha512_final(Sha512Ctx* c, uint8_t out[64]);

}  // namespace tm
