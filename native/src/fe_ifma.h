// 8-way vectorized GF(2^255-19) arithmetic via AVX-512 IFMA
// (vpmadd52{lo,hi}uq): limb j of eight independent field elements lives
// in one zmm register, radix 2^51 exactly like the scalar `fe` type.
//
// Used ONLY for the data-parallel (p-5)/8 power chain inside batched
// point decompression — the dominant per-point cost of RLC batch
// verification. All acceptance/rejection decisions stay in the scalar
// code paths, which are the semantic reference.
//
// IFMA multiplies the LOW 52 bits of each 64-bit lane; every fe8 input
// limb must therefore be < 2^52. fe8_mul's outputs are carried to
// < 2^51 + eps, and the scalar fe_mul/fe_carry producers guarantee the
// same, so the invariant holds by construction.
#pragma once

#if defined(__AVX512IFMA__) && defined(__AVX512VL__) && defined(__AVX512DQ__)
#define TM_HAVE_FE8 1

#include <immintrin.h>
#include <cstdint>

// GCC 12's avx512 intrinsic headers trip -W(maybe-)uninitialized via
// _mm512_undefined_epi32 in their inline fallback paths — a known
// header false positive; keep the project build warning-clean
#pragma GCC diagnostic push
#pragma GCC diagnostic ignored "-Wmaybe-uninitialized"
#pragma GCC diagnostic ignored "-Wuninitialized"

namespace tm {

struct fe8 {
  __m512i v[5];
};

static inline __m512i fe8_mask51() {
  return _mm512_set1_epi64((1LL << 51) - 1);
}

// load limb-sliced: in[i] is a scalar fe (uint64_t[5]); lane k of
// register j gets in[k][j]
static inline void fe8_load(fe8* o, const uint64_t in[8][5]) {
  for (int j = 0; j < 5; j++) {
    alignas(64) uint64_t lane[8];
    for (int k = 0; k < 8; k++) lane[k] = in[k][j];
    o->v[j] = _mm512_load_si512((const void*)lane);
  }
}

static inline void fe8_store(uint64_t out[8][5], const fe8* a) {
  for (int j = 0; j < 5; j++) {
    alignas(64) uint64_t lane[8];
    _mm512_store_si512((void*)lane, a->v[j]);
    for (int k = 0; k < 8; k++) out[k][j] = lane[k];
  }
}

// o = a * b (schoolbook, columns split into IFMA lo/hi parts).
// Each 52x52->104 product contributes low52 at its own column weight
// and high52 doubled at the next column (2^52 = 2*2^51).
static inline void fe8_mul(fe8* o, const fe8* a, const fe8* b) {
  __m512i zero = _mm512_setzero_si512();
  __m512i lo[9], hi[9];
  for (int k = 0; k < 9; k++) lo[k] = hi[k] = zero;
  for (int i = 0; i < 5; i++)
    for (int j = 0; j < 5; j++) {
      lo[i + j] = _mm512_madd52lo_epu64(lo[i + j], a->v[i], b->v[j]);
      hi[i + j] = _mm512_madd52hi_epu64(hi[i + j], a->v[i], b->v[j]);
    }
  // t[k] = lo[k] + 2*hi[k-1]; bounds: 5*2^52 + 2*5*2^52 < 2^56
  __m512i t[9];
  t[0] = lo[0];
  for (int k = 1; k < 9; k++)
    t[k] = _mm512_add_epi64(lo[k], _mm512_slli_epi64(hi[k - 1], 1));
  // fold columns 5..8 down with *19 (2^255 == 19 mod p);
  // 19*t < 2^61, sums < 2^62 — well inside 64 bits
  __m512i nineteen = _mm512_set1_epi64(19);
  for (int k = 5; k < 9; k++)
    t[k - 5] = _mm512_add_epi64(t[k - 5], _mm512_mullo_epi64(t[k], nineteen));
  // also fold 2*hi[8] (weight 2^(51*9)): 51*9 = 255 + 51*4 -> column 4, *19
  t[4] = _mm512_add_epi64(
      t[4], _mm512_mullo_epi64(_mm512_slli_epi64(hi[8], 1), nineteen));
  // carry chain to limbs < 2^52
  __m512i m = fe8_mask51();
  __m512i c;
  for (int j = 0; j < 4; j++) {
    c = _mm512_srli_epi64(t[j], 51);
    t[j] = _mm512_and_epi64(t[j], m);
    t[j + 1] = _mm512_add_epi64(t[j + 1], c);
  }
  c = _mm512_srli_epi64(t[4], 51);
  t[4] = _mm512_and_epi64(t[4], m);
  t[0] = _mm512_add_epi64(t[0], _mm512_mullo_epi64(c, nineteen));
  c = _mm512_srli_epi64(t[0], 51);
  t[0] = _mm512_and_epi64(t[0], m);
  t[1] = _mm512_add_epi64(t[1], c);
  for (int j = 0; j < 5; j++) o->v[j] = t[j];
}

// squaring: 15 distinct products (10 off-diagonal doubled + 5 diagonal)
// instead of fe8_mul's 25. Doubling happens at column combine — the
// operands themselves must stay < 2^52 for IFMA.
static inline void fe8_sq(fe8* o, const fe8* a) {
  __m512i zero = _mm512_setzero_si512();
  __m512i dlo[9], dhi[9], slo[9], shi[9];
  for (int k = 0; k < 9; k++) dlo[k] = dhi[k] = slo[k] = shi[k] = zero;
  for (int i = 0; i < 5; i++) {
    slo[2 * i] = _mm512_madd52lo_epu64(slo[2 * i], a->v[i], a->v[i]);
    shi[2 * i] = _mm512_madd52hi_epu64(shi[2 * i], a->v[i], a->v[i]);
    for (int j = i + 1; j < 5; j++) {
      dlo[i + j] = _mm512_madd52lo_epu64(dlo[i + j], a->v[i], a->v[j]);
      dhi[i + j] = _mm512_madd52hi_epu64(dhi[i + j], a->v[i], a->v[j]);
    }
  }
  // t[k] = slo[k] + 2*dlo[k] + 2*shi[k-1] + 4*dhi[k-1]
  // bounds: 2^52 + 2^54 + 2^53 + 2^55 < 2^56
  __m512i t[9];
  t[0] = _mm512_add_epi64(slo[0], _mm512_slli_epi64(dlo[0], 1));
  for (int k = 1; k < 9; k++) {
    __m512i cur = _mm512_add_epi64(slo[k], _mm512_slli_epi64(dlo[k], 1));
    __m512i carry = _mm512_add_epi64(_mm512_slli_epi64(shi[k - 1], 1),
                                     _mm512_slli_epi64(dhi[k - 1], 2));
    t[k] = _mm512_add_epi64(cur, carry);
  }
  __m512i nineteen = _mm512_set1_epi64(19);
  for (int k = 5; k < 9; k++)
    t[k - 5] = _mm512_add_epi64(t[k - 5], _mm512_mullo_epi64(t[k], nineteen));
  // top hi parts at column 9: 2*shi[8] + 4*dhi[8] -> *19 into column 4
  __m512i top = _mm512_add_epi64(_mm512_slli_epi64(shi[8], 1),
                                 _mm512_slli_epi64(dhi[8], 2));
  t[4] = _mm512_add_epi64(t[4], _mm512_mullo_epi64(top, nineteen));
  __m512i m = fe8_mask51();
  __m512i c;
  for (int j = 0; j < 4; j++) {
    c = _mm512_srli_epi64(t[j], 51);
    t[j] = _mm512_and_epi64(t[j], m);
    t[j + 1] = _mm512_add_epi64(t[j + 1], c);
  }
  c = _mm512_srli_epi64(t[4], 51);
  t[4] = _mm512_and_epi64(t[4], m);
  t[0] = _mm512_add_epi64(t[0], _mm512_mullo_epi64(c, nineteen));
  c = _mm512_srli_epi64(t[0], 51);
  t[0] = _mm512_and_epi64(t[0], m);
  t[1] = _mm512_add_epi64(t[1], c);
  for (int j = 0; j < 5; j++) o->v[j] = t[j];
}

// o = a^(2^252 - 3), the (p-5)/8 exponent — same addition chain as the
// scalar fe_pow2523, eight elements at a time.
static inline void fe8_pow2523(fe8* o, const fe8* z) {
  fe8 t0, t1, t2;
  fe8_sq(&t0, z);
  fe8_sq(&t1, &t0); fe8_sq(&t1, &t1);
  fe8_mul(&t1, z, &t1);
  fe8_mul(&t0, &t0, &t1);
  fe8_sq(&t0, &t0);
  fe8_mul(&t0, &t1, &t0);
  fe8_sq(&t1, &t0);
  for (int i = 1; i < 5; i++) fe8_sq(&t1, &t1);
  fe8_mul(&t0, &t1, &t0);
  fe8_sq(&t1, &t0);
  for (int i = 1; i < 10; i++) fe8_sq(&t1, &t1);
  fe8_mul(&t1, &t1, &t0);
  fe8_sq(&t2, &t1);
  for (int i = 1; i < 20; i++) fe8_sq(&t2, &t2);
  fe8_mul(&t1, &t2, &t1);
  fe8_sq(&t1, &t1);
  for (int i = 1; i < 10; i++) fe8_sq(&t1, &t1);
  fe8_mul(&t0, &t1, &t0);
  fe8_sq(&t1, &t0);
  for (int i = 1; i < 50; i++) fe8_sq(&t1, &t1);
  fe8_mul(&t1, &t1, &t0);
  fe8_sq(&t2, &t1);
  for (int i = 1; i < 100; i++) fe8_sq(&t2, &t2);
  fe8_mul(&t1, &t2, &t1);
  fe8_sq(&t1, &t1);
  for (int i = 1; i < 50; i++) fe8_sq(&t1, &t1);
  fe8_mul(&t0, &t1, &t0);
  fe8_sq(&t0, &t0); fe8_sq(&t0, &t0);
  fe8_mul(o, &t0, z);
}

// o = a + b, lane-wise, NO carry: the result can reach 2^53 per limb,
// which vpmadd52 would silently truncate — callers MUST fe8_carry
// before using the sum as any fe8_mul/fe8_sq operand (unlike the scalar
// fe_add/fe_mul pair, whose u128 math tolerates loose limbs).
static inline void fe8_add(fe8* o, const fe8* a, const fe8* b) {
  for (int j = 0; j < 5; j++) o->v[j] = _mm512_add_epi64(a->v[j], b->v[j]);
}

static inline void fe8_carry(fe8* o);

// o = a - b with the same 2p bias as the scalar fe_sub — but ALWAYS
// carried: vpmadd52 truncates its operands to 52 bits, so unlike the
// scalar code (whose u128 fe_mul tolerates loose < 2^53 limbs) every
// fe8 value that can reach a multiply must stay < 2^52.
static inline void fe8_sub(fe8* o, const fe8* a, const fe8* b) {
  const __m512i bias0 = _mm512_set1_epi64(0xFFFFFFFFFFFDAULL);
  const __m512i bias = _mm512_set1_epi64(0xFFFFFFFFFFFFEULL);
  o->v[0] = _mm512_sub_epi64(_mm512_add_epi64(a->v[0], bias0), b->v[0]);
  for (int j = 1; j < 5; j++)
    o->v[j] = _mm512_sub_epi64(_mm512_add_epi64(a->v[j], bias), b->v[j]);
  fe8_carry(o);
}

static inline void fe8_carry(fe8* o) {
  __m512i m = fe8_mask51();
  __m512i c;
  for (int j = 0; j < 4; j++) {
    c = _mm512_srli_epi64(o->v[j], 51);
    o->v[j] = _mm512_and_epi64(o->v[j], m);
    o->v[j + 1] = _mm512_add_epi64(o->v[j + 1], c);
  }
  c = _mm512_srli_epi64(o->v[4], 51);
  o->v[4] = _mm512_and_epi64(o->v[4], m);
  o->v[0] = _mm512_add_epi64(
      o->v[0], _mm512_mullo_epi64(c, _mm512_set1_epi64(19)));
  c = _mm512_srli_epi64(o->v[0], 51);
  o->v[0] = _mm512_and_epi64(o->v[0], m);
  o->v[1] = _mm512_add_epi64(o->v[1], c);
}

static inline void fe8_blend(fe8* o, __mmask8 k, const fe8* a,
                             const fe8* b) {
  // lane: k ? b : a
  for (int j = 0; j < 5; j++)
    o->v[j] = _mm512_mask_blend_epi64(k, a->v[j], b->v[j]);
}

static inline void fe8_broadcast(fe8* o, const uint64_t a[5]) {
  for (int j = 0; j < 5; j++) o->v[j] = _mm512_set1_epi64(a[j]);
}

// 8 independent extended-Edwards points, limb-sliced like fe8
struct ge8 {
  fe8 X, Y, Z, T;
};

// gather/scatter a ge8 from 8 scalar `ge` structs living at byte
// offsets `off` (per lane) from `base`; ge layout = X[5] Y[5] Z[5] T[5]
// contiguous uint64, 160 bytes
static inline void ge8_gather(ge8* o, const void* base, __m512i off) {
  fe8* f[4] = {&o->X, &o->Y, &o->Z, &o->T};
  for (int fi = 0; fi < 4; fi++)
    for (int j = 0; j < 5; j++)
      f[fi]->v[j] = _mm512_i64gather_epi64(
          _mm512_add_epi64(off, _mm512_set1_epi64((fi * 5 + j) * 8)),
          (const long long*)base, 1);
}

static inline void ge8_mask_scatter(void* base, __mmask8 k, __m512i off,
                                    const ge8* a) {
  const fe8* f[4] = {&a->X, &a->Y, &a->Z, &a->T};
  for (int fi = 0; fi < 4; fi++)
    for (int j = 0; j < 5; j++)
      _mm512_mask_i64scatter_epi64(
          (long long*)base, k,
          _mm512_add_epi64(off, _mm512_set1_epi64((fi * 5 + j) * 8)),
          f[fi]->v[j], 1);
}

// full extended add, 8 lanes (same unified formulas as scalar ge_add);
// d2 = broadcast of FE_D2
static inline void ge8_add(ge8* o, const ge8* p, const ge8* q,
                           const fe8* d2) {
  fe8 a, b, c, d, e, f, g, h, t;
  fe8_sub(&a, &p->Y, &p->X);
  fe8_sub(&t, &q->Y, &q->X);
  fe8_mul(&a, &a, &t);
  fe8_add(&b, &p->Y, &p->X); fe8_carry(&b);
  fe8_add(&t, &q->Y, &q->X); fe8_carry(&t);
  fe8_mul(&b, &b, &t);
  fe8_mul(&c, &p->T, &q->T);
  fe8_mul(&c, &c, d2);
  fe8_mul(&d, &p->Z, &q->Z);
  fe8_add(&d, &d, &d); fe8_carry(&d);
  fe8_sub(&e, &b, &a);
  fe8_sub(&f, &d, &c);
  fe8_add(&g, &d, &c); fe8_carry(&g);
  fe8_add(&h, &b, &a); fe8_carry(&h);
  fe8_mul(&o->X, &e, &f);
  fe8_mul(&o->Y, &g, &h);
  fe8_mul(&o->Z, &f, &g);
  fe8_mul(&o->T, &e, &h);
}

// extended-coords doubling, 8 lanes (same formula as scalar ge_double:
// 4S + 4M). Carry discipline mirrors ge8_add: sums that feed a mul are
// carried explicitly, fe8_sub outputs are mul-safe by construction.
static inline void ge8_dbl(ge8* o, const ge8* p) {
  fe8 a, b, c, e, f, g, h, t;
  fe8_sq(&a, &p->X);
  fe8_sq(&b, &p->Y);
  fe8_sq(&c, &p->Z);
  fe8_add(&c, &c, &c); fe8_carry(&c);
  fe8_add(&h, &a, &b); fe8_carry(&h);
  fe8_add(&t, &p->X, &p->Y); fe8_carry(&t);
  fe8_sq(&t, &t);
  fe8_sub(&e, &h, &t);
  fe8_sub(&g, &a, &b);
  fe8_add(&f, &c, &g); fe8_carry(&f);
  fe8_mul(&o->X, &e, &f);
  fe8_mul(&o->Y, &g, &h);
  fe8_mul(&o->Z, &f, &g);
  fe8_mul(&o->T, &e, &h);
}

// mixed add/sub against ONE shared affine-niels point, with a per-lane
// sign mask (neg lane k=1 -> subtract): the niels multiplier operands
// swap and the C term flips sign, exactly the scalar ge_madd/ge_msub
// pair fused with blends.
static inline void ge8_madd_signed(ge8* o, const ge8* p,
                                   const fe8* yplusx, const fe8* yminusx,
                                   const fe8* xy2d, __mmask8 neg) {
  fe8 qa, qb, a, b, c, d, e, f, g, h, sum, diff;
  fe8_blend(&qa, neg, yminusx, yplusx);  // a-mult: pos->y-x, neg->y+x
  fe8_blend(&qb, neg, yplusx, yminusx);  // b-mult: pos->y+x, neg->y-x
  fe8_sub(&a, &p->Y, &p->X);
  fe8_mul(&a, &a, &qa);
  fe8_add(&b, &p->Y, &p->X); fe8_carry(&b);
  fe8_mul(&b, &b, &qb);
  fe8_mul(&c, &p->T, xy2d);
  fe8_add(&d, &p->Z, &p->Z); fe8_carry(&d);
  fe8_sub(&e, &b, &a);
  fe8_add(&sum, &d, &c); fe8_carry(&sum);  // d + c
  fe8_sub(&diff, &d, &c);                  // d - c
  fe8_blend(&f, neg, &diff, &sum);  // madd: f = d - c; msub: f = d + c
  fe8_blend(&g, neg, &sum, &diff);  // madd: g = d + c; msub: g = d - c
  fe8_add(&h, &b, &a); fe8_carry(&h);
  fe8_mul(&o->X, &e, &f);
  fe8_mul(&o->Y, &g, &h);
  fe8_mul(&o->Z, &f, &g);
  fe8_mul(&o->T, &e, &h);
}

}  // namespace tm

#pragma GCC diagnostic pop

#endif  // AVX512IFMA
