"""Network chaos bench (round 12): recovery time and committed-tx
throughput of a REAL-TCP testnet under link faults (docs/secure-p2p.md).

The device-plane chaos bench (BENCH_r08) measured how one process rides
a sick chip; this one measures how a NETWORK of full nodes — real
listeners, the in-repo SecretConnection encrypting every byte, every
link relayed through ops/netfaults proxies — rides a broken wire:

Rows:
- baseline:       committed heights/s and committed tx/s, fault-free
- partition_heal: seconds from heal() until the chain commits 2 fresh
                  heights on every node (re-peering + re-proposing),
                  median over N_CYCLES halt/heal cycles
- churn:          committed tx/s while rolling listener kill/restart
                  churns one node at a time (+ delta vs baseline)

Asserted floors (chip-free — this gates `make net-chaos-smoke` in
tier1):
- the partitioned chain actually HALTS (safety: no quorum, no commits)
- every cycle recovers: heal-to-commit <= MAX_RECOVERY_S (default 30 s;
  measured ~1-3 s with the bench's tight reconnect cadence)
- final byte-identical convergence across every node (block hash,
  part-set root, app hash per height)

BENCH_NETCHAOS_SMOKE=1 shrinks the net to 4 nodes / 1 cycle for the
tier-1 gate (~35 s). Prints ONE JSON line like the other benches;
writes BENCH_r12.json on full runs.
Run from the repo root: python benches/bench_netchaos.py
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

SMOKE = os.environ.get("BENCH_NETCHAOS_SMOKE", "") == "1"
N_NODES = int(os.environ.get("BENCH_NETCHAOS_NODES", "4" if SMOKE else "5"))
N_CYCLES = int(os.environ.get("BENCH_NETCHAOS_CYCLES", "1" if SMOKE else "3"))
BASE_S = float(os.environ.get("BENCH_NETCHAOS_BASE_S", "6" if SMOKE else "12"))
MAX_RECOVERY_S = float(os.environ.get("BENCH_NETCHAOS_MAX_RECOVERY_S", "30"))


def _pump_txs(net, tag: str, n: int) -> None:
    for i in range(n):
        net.broadcast_tx(f"{tag}-{i}={i}".encode(), via=i % len(net.nodes))


def _committed_txs(net, upto: int) -> int:
    store = net.nodes[0].block_store
    return sum(
        store.load_block(h).header.num_txs for h in range(1, upto + 1)
    )


def main() -> None:
    # hermetic like tests/conftest.py: never dial a production daemon,
    # and pin the CPU platform before jax loads
    os.environ.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
    os.environ.setdefault("TENDERMINT_TPU_PLATFORM", "cpu")

    from netchaos_common import ChaosNet, wait_until

    root = tempfile.mkdtemp(prefix="bench-netchaos-")
    net = ChaosNet(N_NODES, root)
    rows = []
    try:
        t0 = time.perf_counter()
        net.start()
        assert net.wait_height(2, timeout=120), net.heights()
        boot_s = time.perf_counter() - t0

        # -- baseline ------------------------------------------------------
        h0 = min(net.heights())
        tx0 = _committed_txs(net, h0)
        t0 = time.perf_counter()
        deadline = t0 + BASE_S
        i = 0
        while time.perf_counter() < deadline:
            _pump_txs(net, f"base{i}", 20)
            i += 1
            time.sleep(0.1)
        assert net.wait_height(min(net.heights()) + 1, timeout=60)
        base_wall = time.perf_counter() - t0
        h1 = min(net.heights())
        base_heights_s = (h1 - h0) / base_wall
        base_tx_s = (_committed_txs(net, h1) - tx0) / base_wall
        rows.append({
            "mode": "baseline", "nodes": N_NODES, "boot_s": round(boot_s, 2),
            "heights_per_s": round(base_heights_s, 3),
            "committed_tx_per_s": round(base_tx_s, 1),
        })

        # -- partition-heal cycles ----------------------------------------
        recoveries = []
        for c in range(N_CYCLES):
            # a split with no +2/3 side must HALT the chain
            net.partition(set(range((N_NODES // 2) + (N_NODES % 2), N_NODES)))
            h_stall = max(net.heights())
            time.sleep(1.5)
            assert max(net.heights()) <= h_stall + 1, (
                "chain committed through a quorumless partition"
            )
            stalled = max(net.heights())
            t0 = time.perf_counter()
            net.heal()
            assert net.wait_height(stalled + 2, timeout=MAX_RECOVERY_S), (
                f"cycle {c}: no recovery within {MAX_RECOVERY_S}s "
                f"({net.heights()})"
            )
            recoveries.append(time.perf_counter() - t0)
        rows.append({
            "mode": "partition_heal", "cycles": N_CYCLES,
            "recovery_s_median": round(statistics.median(recoveries), 2),
            "recovery_s_max": round(max(recoveries), 2),
            "asserted_max_s": MAX_RECOVERY_S,
        })

        # -- churn throughput ---------------------------------------------
        h0 = min(net.heights())
        tx0 = _committed_txs(net, h0)
        t0 = time.perf_counter()
        for c in range(max(1, N_CYCLES)):
            net.churn_listener((c % (N_NODES - 1)) + 1, down_s=0.4)
            _pump_txs(net, f"churn{c}", 30)
            assert net.wait_height(max(net.heights()) + 1, timeout=60)
        assert wait_until(
            lambda: all(n.sw.peers.size() >= N_NODES - 2 for n in net.nodes),
            timeout=60,
        ), [n.sw.peers.size() for n in net.nodes]
        churn_wall = time.perf_counter() - t0
        h1 = min(net.heights())
        churn_tx_s = (_committed_txs(net, h1) - tx0) / churn_wall
        rows.append({
            "mode": "churn", "churns": max(1, N_CYCLES),
            "committed_tx_per_s": round(churn_tx_s, 1),
            "vs_baseline": round(churn_tx_s / base_tx_s, 2) if base_tx_s else None,
        })

        # -- final byte-identity ------------------------------------------
        top = min(net.heights())
        net.assert_converged(top)
        rows.append({"mode": "convergence", "upto_height": top, "ok": True})
    finally:
        net.stop()

    record = {
        "bench": "netchaos",
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": "cpu",
        "smoke": SMOKE,
        "rows": rows,
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r12.json"), "w") as f:
            json.dump(record, f, indent=2)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
