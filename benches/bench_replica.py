"""Verified read-replica bench (round 24): reads/s and relayed WS
events/s vs replica count, against a live 4-node process localnet
(docs/serving.md § Read replicas).

Two parts:

1. The `replica_flood` ops/localnet scenario — always runs. A 4-node
   fleet, two verified replica processes plus one TAMPERING one behind
   node 0; the scenario asserts the validator's commit cadence stays
   flat under the read flood, replica-served blocks are byte-identical
   to the validator's, the replica_* scrape rows move with zero proof
   failures, and a verifying client rejects 100% of reads from the
   tampered replica.

2. The serving ladder (full runs only) — direct-to-validator vs 1/2/4
   replicas, a fleet of keep-alive flood clients issuing verified
   (prove=1) hot-key reads plus WS NewBlock subscribers, measuring
   aggregate reads/s, relayed events/s, and the validator's commit
   cadence during each window. The fleet runs the docs/serving.md
   PRODUCTION posture: validators arm the round-23 per-IP read budget
   (`TENDERMINT_RPC_RATE_LIMIT`) because a validator's job is
   consensus, not serving — so direct reads/s is the admission budget
   (the rest is typed 429s) on ANY hardware, while each replica
   brings its own unthrottled proof-carrying cache. The CDN claim in
   numbers: replicas serve the reads the validator refuses, and its
   commit cadence stays ~1.0 because it sees none of the flood.
   (Flood clients are paced — the sim-daemon trick from BENCH_r21:
   hold per-client offered load constant so serving capacity, not
   this box's core count, is the measured variable.)

BENCH_REPLICA_SMOKE=1 shrinks to the scenario alone (~60-90 s) for the
tier-1 gate (`make replica-smoke`). Prints ONE JSON line; writes
BENCH_r24.json on full runs. Run from the repo root:
python benches/bench_replica.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_REPLICA_SMOKE", "") == "1"

LADDER = [0, 1, 2, 4]  # 0 = direct-to-validator
CLIENTS = 24  # keep-alive flood clients, spread across endpoints
WS_SUBS = 8  # NewBlock subscribers, spread across endpoints
PACE_S = 0.1  # per-client pacing: <=10 reads/s each, ~240/s offered
WINDOW_S = 12.0  # measured flood window per rung
SEED_KEYS = 8
# the validators' protective per-IP read budget (reads/s) — the
# docs/serving.md posture; the flood offers ~5x this, so the direct
# rung measures what the validator ADMITS, not what clients want
VALIDATOR_READ_BUDGET = 50


def _raise_nofile(want: int) -> None:
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < want:
        resource.setrlimit(resource.RLIMIT_NOFILE, (min(want, hard), hard))


def _read_worker(port: int, keys, stop, out, idx: int) -> None:
    """One keep-alive client hammering verified hot-key reads."""
    from tendermint_tpu.rpc.client import HTTPClient

    c = HTTPClient(f"127.0.0.1:{port}")
    n = 0
    i = idx  # stagger the key rotation across clients
    while not stop.is_set():
        try:
            c.abci_query(data=keys[i % len(keys)].hex(), path="",
                         height=0, prove=True)
            n += 1
        except Exception:  # noqa: BLE001 — shed/refused under load
            pass
        time.sleep(PACE_S)
        i += 1
    out[idx] = n
    c.close()


def _event_worker(port: int, stop, out, idx: int) -> None:
    """One NewBlock subscriber counting relayed events."""
    import queue

    from tendermint_tpu.rpc.client import WSClient

    n = 0
    try:
        ws = WSClient(f"127.0.0.1:{port}")
        ws.subscribe("NewBlock")
        while not stop.is_set():
            try:
                ws.next_event(timeout=0.5)
                n += 1
            except queue.Empty:
                continue
        ws.close()
    except Exception:  # noqa: BLE001 — a dead subscriber just stops
        pass
    out[idx] = n


def _seed_keys(node, count: int) -> list[bytes]:
    keys = [f"rk{i}".encode() for i in range(count)]
    for i, k in enumerate(keys):
        deadline = time.monotonic() + 60.0
        sent = False
        while not sent and time.monotonic() < deadline:
            try:
                node.rpc("broadcast_tx_async",
                         {"tx": (k + b"=rv%d" % i).hex()})
                sent = True
            except Exception:  # noqa: BLE001
                time.sleep(0.2)
        assert sent, f"seed key {k!r} never admitted"
    return keys


def _measure_cadence(node, heights: int, timeout: float) -> float:
    h0 = node.metrics_height()
    t0 = time.monotonic()
    deadline = t0 + timeout
    while time.monotonic() < deadline:
        if node.metrics_height() >= h0 + heights:
            break
        time.sleep(0.2)
    h1 = node.metrics_height()
    assert h1 >= h0 + heights, f"consensus stalled: {h0} -> {h1}"
    return heights / (time.monotonic() - t0)


def run_ladder() -> list[dict]:
    from tendermint_tpu.ops import fleet
    from tendermint_tpu.ops.localnet import (
        Localnet,
        LocalnetSpec,
        ReplicaProc,
    )
    from tendermint_tpu.rpc.client import HTTPClient
    from tendermint_tpu.rpc.light import LightClient

    _raise_nofile(CLIENTS * 2 + WS_SUBS * 2 + 512)
    root = tempfile.mkdtemp(prefix="bench-replica-")
    spec = LocalnetSpec(
        n=4, root=root, seed=24, base_port=47900,
        # the protective posture: validators budget reads per source
        # IP (round 23) — direct-rung reads/s IS this budget
        extra_env={
            "TENDERMINT_RPC_RATE_LIMIT": str(VALIDATOR_READ_BUDGET),
            "TENDERMINT_RPC_RATE_BURST": str(2 * VALIDATOR_READ_BUDGET),
        },
    )
    net = Localnet(spec)
    rows = []
    try:
        net.generate()
        net.start()
        assert net.wait_height(2, timeout=180.0), net.heights()
        node0 = net.nodes[0]
        keys = _seed_keys(node0, SEED_KEYS)
        # make sure the seeds are committed state before anyone reads
        assert net.wait_height(max(net.heights()) + 2, timeout=120.0)
        baseline_hps = _measure_cadence(node0, 5, timeout=300.0)
        rep_base = spec.base_port + 2 * spec.n + 40
        direct_rps = 0.0
        for count in LADDER:
            replicas: list[ReplicaProc] = [
                ReplicaProc(
                    os.path.join(root, f"ladder{count}-{i}"),
                    node0.rpc_url, rep_base + i,
                    # replicas are the serving tier: no read budget
                    extra_env={
                        "TENDERMINT_RPC_WS_MAX_CLIENTS": "512",
                        "TENDERMINT_RPC_RATE_LIMIT": "0",
                    },
                )
                for i in range(count)
            ]
            try:
                for r in replicas:
                    r.start()
                for r in replicas:
                    deadline = time.monotonic() + 120.0
                    while r.lag() != 0 and time.monotonic() < deadline:
                        time.sleep(0.25)
                    if r.lag() != 0:
                        try:
                            st = r.rpc("status", {})
                        except Exception as exc:  # noqa: BLE001
                            st = repr(exc)
                        raise AssertionError(
                            f"replica :{r.rpc_port} never caught up: "
                            f"{st} alive={r.alive()}")
                ports = [r.rpc_port for r in replicas] or [node0.rpc_port]
                stop = threading.Event()
                reads = [0] * CLIENTS
                events = [0] * WS_SUBS
                workers = [
                    threading.Thread(
                        target=_read_worker, daemon=True,
                        args=(ports[i % len(ports)], keys, stop, reads, i))
                    for i in range(CLIENTS)
                ] + [
                    threading.Thread(
                        target=_event_worker, daemon=True,
                        args=(ports[i % len(ports)], stop, events, i))
                    for i in range(WS_SUBS)
                ]
                try:
                    for th in workers:
                        th.start()
                    h0 = node0.metrics_height()
                    t0 = time.monotonic()
                    time.sleep(WINDOW_S)
                    window = time.monotonic() - t0
                    h1 = node0.metrics_height()
                finally:
                    stop.set()
                    for th in workers:
                        th.join(timeout=15)
                flood_hps = max(0, h1 - h0) / window
                rps = sum(reads) / window
                eps = sum(events) / window
                row = {
                    "mode": "direct" if count == 0 else f"replicas:{count}",
                    "replicas": count,
                    "flood_clients": CLIENTS,
                    "ws_subscribers": WS_SUBS,
                    "window_s": round(window, 1),
                    "reads_per_s": round(rps, 1),
                    "ws_events_per_s": round(eps, 1),
                    "baseline_heights_per_s": round(baseline_hps, 3),
                    "flood_heights_per_s": round(flood_hps, 3),
                    "cadence_ratio": round(
                        baseline_hps / flood_hps if flood_hps else 99.0, 3),
                }
                if count == 0:
                    direct_rps = rps
                else:
                    row["speedup_vs_direct"] = round(
                        rps / direct_rps if direct_rps else 0.0, 2)
                    # sampled client-side verification: the flood's
                    # bytes check out against validator-signed headers
                    lc = LightClient.from_genesis(
                        HTTPClient(f"127.0.0.1:{ports[0]}"))
                    res = lc.verified_query(keys[3])
                    assert res["value"] == b"rv3", res
                    row["verified_sample_ok"] = True
                    m = fleet.fetch_metrics(f"127.0.0.1:{ports[0]}")
                    assert (fleet.metric_value(
                        m, "replica_proof_verify_failures", default=0)
                        or 0) == 0
                    row["replica_cache_hits"] = int(fleet.metric_value(
                        m, "replica_cache_hits", default=0) or 0)
                rows.append(row)
            finally:
                for r in replicas:
                    r.kill()
    finally:
        net.stop(keep_root=os.environ.get('BENCH_REPLICA_KEEP_ROOT', '') == '1')
    return rows


def main() -> None:
    os.environ.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
    os.environ.setdefault("TENDERMINT_TPU_PLATFORM", "cpu")

    from tendermint_tpu.ops.localnet import LocalnetSpec, run_scenario

    rows = []
    # part 1 — the replica_flood scenario: flood absorption, cadence,
    # byte identity, scrape visibility, and the 100% tamper rejection
    root = tempfile.mkdtemp(prefix="bench-replica-flood-")
    spec = LocalnetSpec(n=4, root=root, seed=24, base_port=47800)
    t0 = time.perf_counter()
    r = run_scenario(
        spec, "replica_flood", heights=3 if SMOKE else 5,
        keep_root=os.environ.get("BENCH_REPLICA_KEEP_ROOT", "") == "1",
    )
    rows.append({
        "mode": "replica_flood:n=4",
        "replicas": r["replicas"],
        "baseline_heights_per_s": r["baseline_heights_per_s"],
        "flood_heights_per_s": r["flood_heights_per_s"],
        "cadence_ratio": r["cadence_ratio"],
        "replica_reads_served": r["replica_reads_served"],
        "replica_cache_hits": r["replica_cache_hits"],
        "tamper_probes": r["tamper_probes"],
        "tamper_rejected": r["tamper_rejected"],
        "tamper_rejection_rate": round(
            r["tamper_rejected"] / r["tamper_probes"], 3),
        "converged_heights": r["converged_heights"],
        "flood_statuses": r["flood_statuses"],
        "wall_s": round(time.perf_counter() - t0, 1),
    })
    assert rows[0]["tamper_rejection_rate"] == 1.0, rows[0]

    # part 2 — the serving ladder (full runs only)
    acceptance = {}
    if not SMOKE:
        ladder_rows = run_ladder()
        rows.extend(ladder_rows)
        by_count = {row["replicas"]: row for row in ladder_rows}
        acceptance = {
            "speedup_at_2_replicas": by_count[2].get("speedup_vs_direct"),
            "cadence_ratio_at_2_replicas": by_count[2]["cadence_ratio"],
            "tamper_rejection_rate": rows[0]["tamper_rejection_rate"],
        }
        assert acceptance["speedup_at_2_replicas"] >= 1.6, acceptance
        assert acceptance["cadence_ratio_at_2_replicas"] <= 1.2, acceptance

    record = {
        "bench": "replica",
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": "cpu",
        "smoke": SMOKE,
        "cores": os.cpu_count(),
        "note": (
            "ladder runs the docs/serving.md production posture: "
            "validators budget reads per source IP "
            f"(TENDERMINT_RPC_RATE_LIMIT={VALIDATOR_READ_BUDGET}), so "
            "the direct rung measures what a consensus-protecting "
            "validator ADMITS; replicas serve unthrottled from their "
            "proof-carrying caches. Flood clients are paced "
            f"({CLIENTS} clients x {1 / PACE_S:.0f}/s offered) so "
            "serving capacity, not this box's core count, is the "
            "variable"
        ),
        "rows": rows,
    }
    if acceptance:
        record["acceptance"] = acceptance
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r24.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
