"""BASELINE config 5: mempool CheckTx burst — 50k txs.

The reference's load shape (`scripts/txs/random.sh` firing random txs at
broadcast_tx): 50k distinct txs pushed through Mempool.check_tx (cache,
CList append, app CheckTx via the local ABCI conn, tx WAL), then a
reap+update commit cycle — the full mempool lifecycle under burst load.

Prints ONE JSON line. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_TXS = int(os.environ.get("BENCH_N_TXS", "50000"))
# reap at most what the burst inserted (BENCH_N_TXS is shared with the
# testnet bench, so small smoke runs would otherwise break the dup assert)
REAP = min(int(os.environ.get("BENCH_REAP", "10000")), N_TXS)


def main() -> None:
    from tendermint_tpu.abci.apps.counter import CounterApp
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.config import test_config
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.mempool.mempool import TxInCacheError
    from tendermint_tpu.proxy.app_conn import AppConnMempool

    cfg = test_config().mempool
    cfg.root_dir = tempfile.mkdtemp(prefix="bench-mempool-")
    app = CounterApp()
    mp = Mempool(cfg, AppConnMempool(LocalClient(app, threading.RLock())))

    txs = [b"%020d" % i for i in range(N_TXS)]

    # -- burst: 50k CheckTx -----------------------------------------------
    t0 = time.perf_counter()
    for tx in txs:
        mp.check_tx(tx)
    burst_s = time.perf_counter() - t0
    assert mp.size() == N_TXS, mp.size()

    # duplicates bounce off the cache without app round-trips
    t0 = time.perf_counter()
    dup_hits = 0
    for tx in txs[:REAP]:
        try:
            mp.check_tx(tx)
        except TxInCacheError:
            dup_hits += 1
    dup_s = time.perf_counter() - t0
    assert dup_hits == REAP

    # -- commit cycle: reap a block's worth, update, recheck the rest -----
    t0 = time.perf_counter()
    reaped = mp.reap(REAP)
    mp.update(1, reaped)
    cycle_s = time.perf_counter() - t0
    assert mp.size() == N_TXS - len(reaped)

    print(
        json.dumps(
            {
                "metric": "mempool_checktx_per_sec",
                "value": round(N_TXS / burst_s, 1),
                "unit": "txs/s",
                "vs_baseline": 1.0,  # host-path bench: no reference numbers exist
                "detail": {
                    "burst_txs": N_TXS,
                    "burst_s": round(burst_s, 3),
                    "dup_reject_per_sec": round(REAP / dup_s, 1),
                    "reap_update_s": round(cycle_s, 3),
                    "reaped": len(reaped),
                    "app": "counter(local)",
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
