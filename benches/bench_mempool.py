"""BASELINE config 5: mempool CheckTx burst — 50k txs.

The reference's load shape (`scripts/txs/random.sh` firing random txs at
broadcast_tx): 50k distinct txs pushed through Mempool.check_tx (cache,
CList append, app CheckTx via the local ABCI conn, tx WAL), then a
reap+update commit cycle — the full mempool lifecycle under burst load.

Prints ONE JSON line. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_TXS = int(os.environ.get("BENCH_N_TXS", "50000"))
# reap at most what the burst inserted (BENCH_N_TXS is shared with the
# testnet bench, so small smoke runs would otherwise break the dup assert)
REAP = min(int(os.environ.get("BENCH_REAP", "10000")), N_TXS)
N_SIGNED = int(os.environ.get("BENCH_SIGNED_TXS", "4096"))


def _signed_scenario() -> dict:
    """BASELINE config 5's TPU dimension: sig-carrying txs through the
    mempool's batched signature gate (SigBatcher -> gateway kernel)
    versus the reference shape — the app verifying one signature per
    CheckTx on CPU (mempool/mempool.go:166-205). Reports both rates and
    the gateway counters so the batch path is provably exercised."""
    import tempfile
    import threading

    from tendermint_tpu.abci.apps.signedkv import (
        SignedKVStoreApp,
        make_sig_tx,
        parse_sig_tx,
    )
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.config import test_config
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.mempool.mempool import SigBatcher
    from tendermint_tpu.ops.gateway import Verifier
    from tendermint_tpu.proxy.app_conn import AppConnMempool

    seeds = [bytes([i + 1]) * 32 for i in range(64)]
    txs = [
        make_sig_tx(seeds[i % 64], b"sk%06d=v%d" % (i, i)) for i in range(N_SIGNED)
    ]
    n_forged = 0
    for i in range(0, N_SIGNED, 97):  # sprinkle forged lanes
        txs[i] = txs[i][:40] + bytes([txs[i][40] ^ 1]) + txs[i][41:]
        n_forged += 1
    n_good = N_SIGNED - n_forged

    def drain(mp, want, timeout=600.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            mp.flush_app_conn()
            if mp.size() == want:
                return True
            time.sleep(0.005)
        return False

    def run_gated(burst, want):
        """One gated CheckTx burst; (elapsed_s, verifier stats delta)."""
        cfg = test_config().mempool
        cfg.root_dir = tempfile.mkdtemp(prefix="bench-mempool-sig-")
        app = SignedKVStoreApp(verify_in_app=False)
        verifier = Verifier(min_tpu_batch=32)
        # max_batch at half the burst: batches fill (so the linger never
        # idles the full bound) and the drain thread's verify of batch k
        # overlaps the producer's intake of batch k+1 — measured faster
        # than one full-burst batch on BOTH clean (less serial latency)
        # and adversarial (smaller per-batch exact-floor passes) shapes
        batcher = SigBatcher(verifier, parse_sig_tx, max_batch=2048,
                             max_wait_s=0.02)
        mp = Mempool(cfg, AppConnMempool(LocalClient(app, threading.RLock())),
                     sig_batcher=batcher)
        # warm the kernel at the bucket the run will actually hit
        # (batches are capped at the batcher's max_batch), off the clock
        verifier.verify_batch([parse_sig_tx(t) for t in burst[:batcher.max_batch]])
        warm_stats = verifier.stats()
        t0 = time.perf_counter()
        for tx in burst:
            mp.check_tx(tx)
        assert drain(mp, want), f"gated drain stalled at {mp.size()}/{want}"
        el = time.perf_counter() - t0
        batcher.stop()
        stats = verifier.stats()
        # numeric counters only: on the devd backend stats() also carries
        # the nested streamed-transport dict, which doesn't difference
        stats = {
            k: stats[k] - warm_stats.get(k, 0)
            for k in stats
            if isinstance(stats[k], (int, float))
        }
        assert app.check_tx_calls == want, (app.check_tx_calls, want)
        return el, stats

    good_txs = [t for i, t in enumerate(txs) if i % 97 != 0]
    # best-of-2 per scenario: this box is single-core, so any background
    # work (e.g. the device daemon's periodic reclaim probe) lands
    # wholly on the bench; min-time damps it
    # clean burst: the RLC fast path decides whole batches — the gate's
    # happy-path rate
    clean_s, clean_stats = min(
        (run_gated(good_txs, len(good_txs)) for _ in range(2)),
        key=lambda r: r[0],
    )
    # adversarial burst (forged lanes sprinkled): one failed RLC routes
    # each batch to the exact 8-wide per-item floor — the gate's
    # flood-resistance rate
    gated_s, stats = min(
        (run_gated(txs, n_good) for _ in range(2)), key=lambda r: r[0]
    )

    # -- reference shape: the app verifies per tx on CPU ------------------
    in_app_s = float("inf")
    for _ in range(2):
        cfg2 = test_config().mempool
        cfg2.root_dir = tempfile.mkdtemp(prefix="bench-mempool-sig-")
        app2 = SignedKVStoreApp(verify_in_app=True)
        mp2 = Mempool(cfg2, AppConnMempool(LocalClient(app2, threading.RLock())))
        t0 = time.perf_counter()
        for tx in txs:
            mp2.check_tx(tx)
        assert drain(mp2, n_good), f"in-app drain stalled at {mp2.size()}/{n_good}"
        in_app_s = min(in_app_s, time.perf_counter() - t0)

    return {
        "signed_txs": N_SIGNED,
        "forged": n_forged,
        "gated_clean_sigs_per_sec": round(len(good_txs) / clean_s, 1),
        "gated_adversarial_sigs_per_sec": round(N_SIGNED / gated_s, 1),
        "in_app_sigs_per_sec": round(N_SIGNED / in_app_s, 1),
        "gate_speedup_clean": round(
            (in_app_s / N_SIGNED) / (clean_s / len(good_txs)), 2
        ),
        "gate_speedup_adversarial": round(in_app_s / gated_s, 2),
        "gateway_stats_clean": clean_stats,
        "gateway_stats_adversarial": stats,
    }


def main() -> None:
    from tendermint_tpu.abci.apps.counter import CounterApp
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.config import test_config
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.mempool.mempool import TxInCacheError
    from tendermint_tpu.proxy.app_conn import AppConnMempool

    cfg = test_config().mempool
    cfg.root_dir = tempfile.mkdtemp(prefix="bench-mempool-")
    app = CounterApp()
    mp = Mempool(cfg, AppConnMempool(LocalClient(app, threading.RLock())))

    txs = [b"%020d" % i for i in range(N_TXS)]

    # -- burst: 50k CheckTx -----------------------------------------------
    t0 = time.perf_counter()
    for tx in txs:
        mp.check_tx(tx)
    burst_s = time.perf_counter() - t0
    assert mp.size() == N_TXS, mp.size()

    # duplicates bounce off the cache without app round-trips
    t0 = time.perf_counter()
    dup_hits = 0
    for tx in txs[:REAP]:
        try:
            mp.check_tx(tx)
        except TxInCacheError:
            dup_hits += 1
    dup_s = time.perf_counter() - t0
    assert dup_hits == REAP

    # -- commit cycle: reap a block's worth, update, recheck the rest -----
    t0 = time.perf_counter()
    reaped = mp.reap(REAP)
    mp.update(1, reaped)
    cycle_s = time.perf_counter() - t0
    assert mp.size() == N_TXS - len(reaped)

    signed = _signed_scenario()
    # Headline (round 5, VERDICT r4 #5): the SIGNED scenario — the
    # framework's accelerated dimension (batched sig gate vs the
    # reference shape of one in-app verify per CheckTx,
    # mempool/mempool.go:166-205) — with vs_baseline = the clean-burst
    # gate speedup. The unsigned 50k burst stays in detail: it measures
    # host-path machinery with no reference number to compare against.
    print(
        json.dumps(
            {
                "metric": "mempool_signed_checktx_per_sec",
                "value": signed["gated_clean_sigs_per_sec"],
                "unit": "txs/s",
                "vs_baseline": signed["gate_speedup_clean"],
                "detail": {
                    "signed": signed,
                    "unsigned_burst": {
                        "burst_txs": N_TXS,
                        "checktx_per_sec": round(N_TXS / burst_s, 1),
                        "burst_s": round(burst_s, 3),
                        "dup_reject_per_sec": round(REAP / dup_s, 1),
                        "reap_update_s": round(cycle_s, 3),
                        "reaped": len(reaped),
                        "app": "counter(local)",
                    },
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
