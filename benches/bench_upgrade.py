"""Upgrade-at-height bench (round 22): the aggregate-commit cutover,
measured on the wire and across a LIVE flip. Writes BENCH_r22.json.

Row families:

- flip:n=4          — the ops/localnet `upgrade` scenario: a real
                      4-process fleet with `upgrade_height` baked into
                      the shared genesis, a laggard SIGKILLed BEFORE
                      the flip, the survivors crossing H without
                      missing a height, one survivor rolled across the
                      boundary, the laggard catching up THROUGH both
                      formats. Per-height byte identity both sides of
                      H and the upgrade_* scrape asserts live inside
                      the scenario (ops/localnet.py). The full run
                      additionally polls node0's public RPC during the
                      flip and reports `flip_stall_x` — the commit
                      interval AT height H over the median interval of
                      the surrounding heights (the "zero missed
                      heights" claim, quantified: a consensus-rule
                      cutover that stalled would spike this number).
- wire:n=100/400    — the cutover's object-level payoff: wire bytes of
                      the full Commit vs the half-aggregated
                      AggregateCommit over the same signed precommits
                      (ASSERTED <= 0.35x at n=100; measured ~0.25x),
                      and the verify-latency A/B the block plane rides
                      after the flip — full per-sig loop vs the
                      gateway-batched dual-scalar-mul aggregate verify
                      vs the pure-python reference. `gateway faster
                      than python` is asserted ONLY when the gateway
                      actually took a device lane (verifier stats
                      `agg_lanes_device` > 0): on a chip-free box the
                      gateway's CPU floor IS the pure-python verifier
                      (ops/gateway.py), so the two rows measure the
                      same code plus dispatch overhead — asserting an
                      ordering there would be noise, not signal (the
                      BENCHES.cpu-fallback.json precedent).

Asserted floors (chip-free — this gates `make upgrade-smoke` in tier1):
- the upgrade scenario converges byte-identically through the flip with
  the laggard recovering (scenario-internal asserts)
- zero schedule refusals inside the homogeneous fleet
- full run: aggregate commit bytes <= 0.35x full at n=100

BENCH_UPGRADE_SMOKE=1 shrinks to the one 4-node flip run (~60-90 s)
for the tier-1 gate. Prints ONE JSON line like the other benches;
writes BENCH_r22.json on full runs. Run from the repo root:
python benches/bench_upgrade.py
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import threading
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_UPGRADE_SMOKE", "") == "1"
WIRE_VALS = [100] if SMOKE else [100, 400]
MAX_BYTES_RATIO = float(os.environ.get("BENCH_UPGRADE_MAX_RATIO", "0.35"))
GENESIS_NS = 1_700_000_000_000_000_000
CHAIN_ID = "bench_upgrade"


def _poll_heights(port: int, seen: dict, stop: threading.Event) -> None:
    """Background poller: height -> first time observed, off node0's
    public RPC. Best-effort — the node may not be up yet, and fast
    commits can skip heights between polls; the consumer only uses
    consecutive observations."""
    body = json.dumps({
        "jsonrpc": "2.0", "id": "bench", "method": "status", "params": {},
    }).encode()
    while not stop.is_set():
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                out = json.loads(resp.read())
            h = int(out["result"]["latest_block_height"])
            if h > 0 and h not in seen:
                seen[h] = time.monotonic()
        except Exception:
            pass
        stop.wait(0.05)


def _flip_stall(seen: dict, H: int):
    """Commit interval at the flip height over the median interval of
    every other consecutively-observed pair. None when the poller
    missed either side of the boundary."""
    dts = {}
    for h in sorted(seen):
        if h - 1 in seen:
            dts[h] = seen[h] - seen[h - 1]
    others = [dt for h, dt in dts.items() if h != H]
    if H not in dts or not others:
        return None
    med = statistics.median(others)
    return round(dts[H] / med, 2) if med > 0 else None


def _flip_row(heights: int, measure_stall: bool) -> dict:
    from tendermint_tpu.ops.localnet import LocalnetSpec, run_scenario

    spec = LocalnetSpec(
        n=4, root=tempfile.mkdtemp(prefix="bench-upgrade-"),
        seed=22, base_port=47700, upgrade_height=4,
    )
    seen: dict = {}
    stop = threading.Event()
    poller = None
    if measure_stall:
        poller = threading.Thread(
            target=_poll_heights, args=(spec.rpc_port(0), seen, stop),
            daemon=True,
        )
        poller.start()
    try:
        t0 = time.perf_counter()
        r = run_scenario(spec, "upgrade", heights=heights)
        wall = time.perf_counter() - t0
    finally:
        stop.set()
        if poller is not None:
            poller.join(timeout=5.0)
    assert r["agg_commit_rejects"] == 0, r
    row = {
        "row": "flip:n=4",
        "nodes": 4,
        "upgrade_height": r["upgrade_height"],
        "converged_heights": r["converged_heights"],
        "laggard_killed_at": r["laggard_killed_at"],
        "agg_commits_proposed": r["agg_commits_proposed"],
        "agg_commit_rejects": r["agg_commit_rejects"],
        "wall_s": round(wall, 1),
    }
    if measure_stall:
        row["flip_stall_x"] = _flip_stall(seen, r["upgrade_height"])
    return row


def _signed_commit(n, height=7):
    """n seeded validators and a fully-signed precommit Commit — the
    object both wire formats are built from."""
    from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.state.state import State
    from tendermint_tpu.types import (
        GenesisDoc, GenesisValidator, PrivValidatorFS,
    )
    from tendermint_tpu.types.block import Commit
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote

    pvs = []
    for i in range(n):
        seed = (b"upgrade-%05d" % i).ljust(32, b"\x00")
        pvs.append(PrivValidatorFS(gen_priv_key_ed25519(seed), None))
    pvs.sort(key=lambda pv: pv.get_address())
    gvals = [GenesisValidator(pv.get_pub_key(), 10, f"v{i}")
             for i, pv in enumerate(pvs)]
    doc = GenesisDoc(genesis_time_ns=GENESIS_NS, chain_id=CHAIN_ID,
                     validators=gvals)
    vals = State.get_state(MemDB(), doc).validators
    bid = BlockID(b"\x22" * 20, PartSetHeader(1, b"\x44" * 20))
    pres = []
    for i, pv in enumerate(pvs):
        v = Vote(pv.get_address(), i, height, 0, VOTE_TYPE_PRECOMMIT, bid)
        pres.append(pv.sign_vote(CHAIN_ID, v))
    return vals, bid, Commit(bid, pres), height


def _wire_rows() -> list:
    from tendermint_tpu.crypto import ed25519_agg
    from tendermint_tpu.ops.gateway import default_verifier
    from tendermint_tpu.types.agg_commit import AggregateCommit

    rows = []
    for n in WIRE_VALS:
        vals, bid, commit, height = _signed_commit(n)
        agg = AggregateCommit.from_commit(commit, CHAIN_ID, vals)
        commit_bytes = len(commit.to_bytes())
        agg_bytes = len(agg.to_bytes())
        ratio = agg_bytes / commit_bytes
        if n == 100:
            assert ratio <= MAX_BYTES_RATIO, (
                f"post-cutover commit wire bytes {ratio:.3f}x full at "
                f"n={n} (ceiling {MAX_BYTES_RATIO}x)"
            )

        dv = default_verifier()
        lanes_before = dv.stats()["agg_lanes_device"]
        t0 = time.perf_counter()
        agg.verify(CHAIN_ID, vals)  # gateway-batched (default verifier)
        gateway_s = time.perf_counter() - t0
        device_lanes = dv.stats()["agg_lanes_device"] - lanes_before
        t0 = time.perf_counter()
        agg.verify(CHAIN_ID, vals,
                   agg_verifier=ed25519_agg.verify_aggregate)
        python_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        vals.verify_commit(CHAIN_ID, bid, height, commit)
        per_sig_s = time.perf_counter() - t0
        if device_lanes > 0:
            # only when a device lane actually served: the chip-free
            # floor IS the python verifier, so the ordering there is
            # dispatch noise (see module docstring)
            assert gateway_s < python_s, (
                f"device-lane aggregate verify slower than pure python "
                f"at n={n}: {gateway_s:.4f}s vs {python_s:.4f}s"
            )
        rows.append({
            "row": f"wire:n={n}",
            "validators": n,
            "commit_bytes": commit_bytes,
            "aggregate_bytes": agg_bytes,
            "bytes_vs_full": round(ratio, 3),
            "verify_gateway_s": round(gateway_s, 4),
            "verify_python_s": round(python_s, 4),
            "full_per_sig_s": round(per_sig_s, 4),
            "agg_lanes_device": device_lanes,
            "platform": "devd" if device_lanes > 0 else "host",
        })
    return rows


def main() -> None:
    os.environ.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
    os.environ.setdefault("TENDERMINT_TPU_PLATFORM", "cpu")

    rows = [_flip_row(heights=4 if SMOKE else 8,
                      measure_stall=not SMOKE)]
    if not SMOKE:
        rows.extend(_wire_rows())

    out = {
        "bench": "upgrade",
        "smoke": SMOKE,
        "max_bytes_ratio_asserted": MAX_BYTES_RATIO,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "rows": rows,
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r22.json"), "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
