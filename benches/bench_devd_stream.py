"""Config 6: devd serving-path transport — single-shot vs streamed.

The r5 live captures pinned the serving-path ceiling at 52.2k sigs/s
("single-shot daemon-side verify per request; the IPC serving path was
the bottleneck, not the kernel") while the in-process pipelined kernel
sustained 119.7k. This bench measures exactly that gap, three ways:

- sim row (ALWAYS, asserted >= MIN_SPEEDUP): a sim-device daemon
  (devd._SimVerifier — FIFO compute at a fixed sigs/s) holds device
  time constant, so single-shot vs streamed isolates the transport:
  pickle-the-world round trips vs chunked frames overlapping marshal,
  IPC, and device compute.
- real row (BENCH_DEVD_REAL=0 to skip): the same comparison against a
  real CPU-kernel daemon — compute-bound, so the gap narrows; recorded
  for honesty, not asserted.
- live row (only when a daemon already serves, e.g. a TPU box): the
  comparison against the held accelerator — the row the next live-chip
  window fills in.

Prints ONE JSON line and writes BENCH_r06.json at the repo root; every
row carries its platform. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ITEMS = int(os.environ.get("BENCH_STREAM_ITEMS", "16384"))
CHUNK = int(os.environ.get("BENCH_STREAM_CHUNK", "2048"))
TRIALS = int(os.environ.get("BENCH_STREAM_TRIALS", "5"))
SIM_RATE = float(os.environ.get("BENCH_STREAM_SIM_RATE", "500000"))
MIN_SPEEDUP = float(os.environ.get("BENCH_STREAM_MIN_SPEEDUP", "1.3"))


def _spawn_daemon(extra_env: dict) -> tuple[subprocess.Popen, str]:
    sock = os.path.join(tempfile.mkdtemp(prefix="bench-devd-"), "devd.sock")
    env = {
        **os.environ,
        "TENDERMINT_DEVD_SOCK": sock,
        "TENDERMINT_DEVD_ACCEPT_CPU": "1",
        "TENDERMINT_DEVD_EXIT_ON_TERM": "1",
        **extra_env,
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.devd"],
        env=env, cwd=ROOT,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    return proc, sock


def _wait_held(client, proc, deadline_s: float) -> None:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            err = proc.stderr.read() if proc.stderr else b""
            raise RuntimeError(f"daemon died: {err[-2000:]!r}")
        try:
            if client.ping(timeout=2.0).get("held"):
                return
        except Exception:  # noqa: BLE001 — still starting
            pass
        time.sleep(0.5)
    raise RuntimeError("daemon never reached serving state")


def _items(n: int, forge_every: int = 0) -> list:
    from tendermint_tpu.crypto import ed25519 as ed

    seeds = [bytes([7, k]) + b"\x07" * 30 for k in range(64)]
    keys = [(s, ed.public_key(s)) for s in seeds]
    base = [
        (
            keys[i % 64][1],
            b"stream-%06d" % i,
            ed.sign(keys[i % 64][0], b"stream-%06d" % i),
        )
        for i in range(min(n, 512))
    ]
    out = [base[i % len(base)] for i in range(n)]
    if forge_every:
        for i in range(0, n, forge_every):
            pk, msg, sig = out[i]
            out[i] = (pk, msg, bytes([sig[0] ^ 1]) + sig[1:])
    return out


def _structural_items(n: int) -> list:
    """Cheap lanes for the sim row (the sim verifier checks structure
    only — real signatures would just burn bench time on keygen)."""
    return [
        (bytes([i % 251]) * 32, b"sim-%06d" % i, bytes([i % 249]) * 64)
        for i in range(n)
    ]


def _measure(client, items, chunk: int, trials: int) -> dict:
    """Best-of-`trials` for each path, alternated so machine noise hits
    both equally. Single-shot = the pre-r6 serving path: the WHOLE batch
    as one pickled request, one monolithic round trip."""
    n = len(items)
    client.verify_batch(items[: min(n, 256)])  # connection + import warm
    client.verify_stream(items[: min(n, 256)], chunk=max(chunk // 8, 32))
    single_best = stream_best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        r1 = client.verify_batch(items)
        single_best = min(single_best, time.perf_counter() - t0)
        t0 = time.perf_counter()
        r2 = client.verify_stream(items, chunk=chunk)
        stream_best = min(stream_best, time.perf_counter() - t0)
        assert list(r1) == list(r2), "streamed verdicts diverge from single-shot"
    return {
        "items": n,
        "chunk": chunk,
        "single_shot_sigs_per_sec": round(n / single_best, 1),
        "streamed_sigs_per_sec": round(n / stream_best, 1),
        "speedup": round(single_best / stream_best, 3),
        "single_shot_ms": round(single_best * 1000, 1),
        "streamed_ms": round(stream_best * 1000, 1),
    }


def main() -> None:
    from tendermint_tpu import devd

    rows = []

    # -- live row: a daemon already serving (e.g. the TPU box) ------------
    live = devd.available(timeout=3.0)
    if live is not None:
        client = devd.DevdClient()
        row = _measure(client, _items(N_ITEMS, forge_every=97), CHUNK, TRIALS)
        row.update(platform=live.get("platform"), mode="live-daemon")
        status = client.status()
        row["daemon_stream"] = status.get("stream", {})
        rows.append(row)
        client.close()

    # -- sim row: transport isolated, device time held constant -----------
    proc, sock = _spawn_daemon({"TENDERMINT_DEVD_SIM_RATE": str(int(SIM_RATE))})
    try:
        client = devd.DevdClient(sock)
        _wait_held(client, proc, 60.0)
        row = _measure(client, _structural_items(N_ITEMS), CHUNK, TRIALS)
        row.update(
            platform="sim", mode="sim-transport",
            sim_device_sigs_per_sec=SIM_RATE,
        )
        row["daemon_stream"] = client.status().get("stream", {})
        rows.append(row)
        client.shutdown()
        client.close()
    finally:
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
    sim_row = rows[-1]

    # -- real row: CPU kernel daemon, compute-bound. Small shapes on
    # purpose: the f32 CPU compile at wide buckets runs minutes on a
    # single-core CI box, and this row exists for verdict-path honesty,
    # not throughput (that's the sim and live rows) -------------------------
    if os.environ.get("BENCH_DEVD_REAL", "1") != "0":
        proc, sock = _spawn_daemon({
            "TENDERMINT_DEVD_WARM": "256",
            "JAX_PLATFORMS": "cpu",
        })
        try:
            client = devd.DevdClient(sock)
            _wait_held(client, proc, 600.0)  # cold .jax_cache: one compile
            row = _measure(
                client, _items(1024, forge_every=97), 256, max(2, TRIALS - 3)
            )
            row.update(platform="cpu", mode="real-cpu-kernel")
            row["daemon_stream"] = client.status().get("stream", {})
            rows.append(row)
            client.shutdown()
            client.close()
        finally:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": "devd serving path: single-shot vs streamed sigs/s",
        "min_speedup_asserted": MIN_SPEEDUP,
        "rows": rows,
        "note": (
            "sim row isolates the IPC transport (device time constant); "
            "rows carry their platform so a live-chip window appends the "
            "TPU row against the same protocol"
        ),
    }
    with open(os.path.join(ROOT, "BENCH_r06.json"), "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")

    print(json.dumps({
        "metric": "devd_streamed_sigs_per_sec",
        "value": sim_row["streamed_sigs_per_sec"],
        "unit": "sigs/s",
        "vs_baseline": sim_row["speedup"],  # vs the single-shot serving path
        "detail": {"rows": rows, "platform": rows[-1]["platform"]},
    }))

    assert sim_row["speedup"] >= MIN_SPEEDUP, (
        f"streamed transport only {sim_row['speedup']}x the single-shot "
        f"path (need >= {MIN_SPEEDUP}x): {sim_row}"
    )


if __name__ == "__main__":
    sys.exit(main())
