"""Run every BASELINE.md config bench and record results in BENCHES.json.

Configs (BASELINE.md):
  1 testnet   — 4-validator kvstore net, commit-hash parity
  2 headline  — VerifyCommit microbench (repo-root bench.py, driver-run)
  3 partset   — 1MB/64KB PartSet Merkle + proofs, plus the r7 hash-plane
                rows: sim-transport streamed-vs-single-shot hash offload
                (asserted >= 1.3x) and flat-vs-recursive host proofs
                builder (asserted >= 1.5x); writes BENCH_r07.json with
                per-row platform, chip-free
  4 fastsync  — pipelined catch-up replay, 1000 validators
  5 mempool   — 50k-tx CheckTx burst + signed-tx gated burst
  6 devd_stream — serving-path transport: single-shot vs streamed devd
                  (writes BENCH_r06.json; asserts the streamed win)
  7 chaos      — device-plane failure shape: recovery time after daemon
                 kill/restart + degraded-mode (breaker-open CPU
                 fallback) throughput delta (writes BENCH_r08.json;
                 chip-free, asserts the recovery floor)
  8 wal        — host durability plane: group-commit vs fsync-per-record
                 WAL throughput, repair/recovery scan on a torn 10k-record
                 log, byte-offset torture smoke (writes BENCH_r09.json;
                 chip-free BY CONSTRUCTION, asserts the >=1.3x floor)
  9 statesync   — cold-start plane: snapshot restore vs fast-sync replay
                  on a 300-block signedkv chain + streamed-vs-single-shot
                  chunk verification on the sim transport (writes
                  BENCH_r10.json; chip-free rows asserted >=1.3x, the
                  live-daemon row auto-appends on a tunnel window)
 10 telemetry    — observability plane: hot-path instrumentation overhead
                  on the mempool signed-burst gate (asserted <2%) +
                  Prometheus exposition smoke (writes the "telemetry"
                  section of BENCH_r11.json; chip-free)
 11 rpc_load     — ws broadcast burst against a live node + the round-11
                  scrape-cost row: GET /metrics hammered under load must
                  not move consensus height_seconds (writes the
                  "rpc_scrape" section of BENCH_r11.json; chip-free)
 12 netchaos     — network plane: real-TCP testnet (in-repo
                  SecretConnection + ops/netfaults link proxies) through
                  partition-heal cycles + listener churn; recovery time
                  and committed-tx/s recorded, halt-under-partition and
                  byte-identical convergence asserted (writes
                  BENCH_r12.json; chip-free)
 14 pipeline     — execution plane: committed-tx/s at saturating signed
                  mempool load on a durable single-validator chain, seed
                  plane (inline finalize + per-tx DeliverTx dispatch +
                  per-tx pure-python sig verify) vs the round-14 plane
                  (staged pipelined finalize + grouped dispatch + one
                  gateway sig batch per block + sharded kv fold); byte-
                  identity of all chains asserted (writes BENCH_r14.json;
                  chip-free)
 15 fleet        — fleet observability plane: 4-node real-TCP net scraped
                  by ops/fleet (GET /metrics + consensus_trace +
                  GET /health only) — cross-node timeline reconstructed
                  (propagation lag / quorum time / commit skew), the
                  partition arm detected+healed off /health, per-peer
                  instrumentation overhead bounded <2% (writes
                  BENCH_r15.json; chip-free)
 16 committee    — big-committee vote plane: live 100-400-validator
                  consensus (in-process committee pump) batched vs
                  per-vote vote-signature verification — byte-identical
                  chains asserted, batched >= 1.3x at 100 validators —
                  plus commit-verify latency and aggregate-commit size
                  rows vs validator count (writes BENCH_r16.json;
                  chip-free, devd rows auto-join when a daemon serves)
 17 txtrace      — request-level observability: sampled per-tx lifecycle
                  spans on a live committing chain (per-stage p50/p99,
                  spans-through-commit asserted within 10% of measured
                  end-to-end latency), tracing + flight-recorder
                  overhead bound asserted <2% on the signed-burst
                  shape, wedge-dump artifact row (writes BENCH_r17.json;
                  chip-free)
 18 wan          — internet-scale adversarial tier: real-TCP testnet
                  under named WAN profiles (seeded latency/jitter/loss/
                  bandwidth via ops/netfaults) — heights/s + commit
                  skew per profile off the ops/fleet timelines — plus
                  the flood-shed row: heights cadence asserted >= 1/3
                  baseline while a hostile peer floods garbage
                  signatures at the sig gate, shed asserted visible in
                  p2p_adversary_flood_txs_rejected (writes
                  BENCH_r18.json; chip-free)
 19 retention    — bounded-retention lifecycle: steady-state disk
                  bytes/height on a pruned vs archive node (asserted
                  bounded by retention, not chain length) + adversarial
                  statesync offerer ban latency (forged / corrupt /
                  stalling each banned while the restore completes from
                  the honest source; writes BENCH_r19.json; chip-free)
 20 localnet     — hundreds-of-nodes process tier: 10/25/50 real node
                  processes (ops/localnet through netfaults proxies,
                  50 under the continental WAN profile on a ring);
                  heights/s, duplicate-vote ratio, gossip bytes/height
                  vs node count; has-vote dedup A/B at n=10 asserted
                  to reduce the ratio; process-scale partition-heal
                  (writes BENCH_r20.json; chip-free)
 21 devd_shard   — sharded device plane: aggregate verify sigs/s + hash
                  MB/s through ops/devd_shard vs 1/2/4 sim daemon
                  fleets (>= 1.6x at 2 daemons asserted, digests
                  byte-identical across fleet sizes) + the
                  kill-one-mid-burst failover row: exact per-lane
                  verdicts through re-dispatch, breaker open/recovery
                  latencies (writes BENCH_r21.json; chip-free)
 13 statetree    — authenticated app-state commitment: incremental
                  commit vs full tree rebuild, proof correctness rows,
                  delta-vs-full snapshot bytes (delta asserted <= 0.5x
                  full at the larger state size), streamed vs
                  single-shot node hashing on the sim transport (writes
                  BENCH_r13.json; chip-free rows asserted, the
                  live-daemon row auto-appends on a tunnel window)

24 replica      — verified read-replica tier: the replica_flood
                  localnet scenario (cadence flat under flood, byte
                  identity, 100% tamper rejection) + the serving
                  ladder — verified reads/s and relayed WS events/s
                  direct-to-validator vs 1/2/4 replica processes
                  (writes BENCH_r24.json; chip-free)

Each bench is its own process (the TPU is exclusive per process).
Usage: python benches/run_all.py [--skip testnet,...]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BENCHES = {
    "1_testnet": [sys.executable, "benches/bench_testnet.py"],
    "2_verify_commit": [sys.executable, "bench.py"],
    "3_partset": [sys.executable, "benches/bench_partset.py"],
    "4_fastsync": [sys.executable, "benches/bench_fastsync.py"],
    "5_mempool": [sys.executable, "benches/bench_mempool.py"],
    "6_devd_stream": [sys.executable, "benches/bench_devd_stream.py"],
    "7_chaos": [sys.executable, "benches/bench_chaos.py"],
    "8_wal": [sys.executable, "benches/bench_wal.py"],
    "9_statesync": [sys.executable, "benches/bench_statesync.py"],
    "10_telemetry": [sys.executable, "benches/bench_telemetry.py"],
    "11_rpc_load": [sys.executable, "benches/bench_rpc_load.py"],
    "12_netchaos": [sys.executable, "benches/bench_netchaos.py"],
    "13_statetree": [sys.executable, "benches/bench_statetree.py"],
    "14_pipeline": [sys.executable, "benches/bench_pipeline.py"],
    "15_fleet": [sys.executable, "benches/bench_fleet.py"],
    "16_committee": [sys.executable, "benches/bench_committee.py"],
    "17_txtrace": [sys.executable, "benches/bench_txtrace.py"],
    "18_wan": [sys.executable, "benches/bench_wan.py"],
    "19_retention": [sys.executable, "benches/bench_retention.py"],
    "20_localnet": [sys.executable, "benches/bench_localnet.py"],
    "21_devd_shard": [sys.executable, "benches/bench_devd_shard.py"],
    "22_upgrade": [sys.executable, "benches/bench_upgrade.py"],
    "23_overload": [sys.executable, "benches/bench_overload.py"],
    "24_replica": [sys.executable, "benches/bench_replica.py"],
}


def main() -> int:
    skip = set()
    for a in sys.argv[1:]:
        if a.startswith("--skip"):
            skip = set(a.split("=", 1)[1].split(","))
    results: dict = {"recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    # one probe up front: a wedged tunnel would otherwise stall EVERY
    # device-dialing sub-bench for its full 30-min timeout (jitcache.probe_device
    # docstring has the failure mode)
    env = dict(os.environ)
    need_direct_probe = env.get("TENDERMINT_TPU_DISABLE", "") != "1"
    if need_direct_probe:
        # a serving device daemon changes the topology: IT holds the chip
        # and every sub-bench routes over IPC (the gateway auto-selects
        # the devd backend), so probing the device directly would contend
        # with the daemon's exclusive session — skip straight to running
        sys.path.insert(0, ROOT)
        from tendermint_tpu import devd

        rep = devd.available(timeout=3.0)
        if rep is not None and rep.get("platform") in ("tpu", "axon"):
            results["device"] = (
                f"devd daemon ({rep.get('platform')}, pid {rep.get('pid')})"
            )
            print(f"run_all: {results['device']}; benches ride the daemon",
                  file=sys.stderr)
            need_direct_probe = False
    if need_direct_probe:
        # throwaway-subprocess probe (devd.subprocess_probe): probing
        # in-process would initialize this parent's jax backend and hold
        # the exclusive device, starving every sub-bench (each bench is
        # its own process precisely because the TPU is exclusive then)
        if devd.subprocess_probe(90.0) is None:
            print(
                "run_all: accelerator unreachable; all benches measure "
                "the CPU fallback",
                file=sys.stderr,
            )
            env["TENDERMINT_TPU_DISABLE"] = "1"
            results["device"] = "unreachable; CPU fallback"
    failed = False
    for name, cmd in BENCHES.items():
        if any(s in name for s in skip):
            continue
        print(f"== {name}: {' '.join(cmd[1:])}", file=sys.stderr)
        t0 = time.time()
        try:
            proc = subprocess.run(
                cmd, cwd=ROOT, capture_output=True, text=True, timeout=1800, env=env
            )
        except subprocess.TimeoutExpired as exc:
            results[name] = {"error": f"timeout after {exc.timeout}s"}
            failed = True
            print(f"   TIMEOUT ({time.time()-t0:.0f}s)", file=sys.stderr)
            continue
        line = next(
            (l for l in reversed(proc.stdout.splitlines()) if l.startswith("{")), None
        )
        if proc.returncode != 0 or line is None:
            results[name] = {"error": (proc.stderr or proc.stdout)[-2000:]}
            failed = True
            print(f"   FAILED ({time.time()-t0:.0f}s)", file=sys.stderr)
            continue
        results[name] = json.loads(line)
        print(f"   {line} ({time.time()-t0:.0f}s)", file=sys.stderr)
    out = os.path.join(ROOT, "BENCHES.json")
    if results.get("device", "").startswith("unreachable"):
        # never clobber a recorded accelerator run with a CPU fallback:
        # BENCHES.json is the standing TPU record (round-3 postmortem —
        # a fallback that overwrites the record reads as a regression)
        try:
            with open(out) as f:
                prior = json.load(f)
        except (OSError, ValueError):
            prior = {}
        if any(
            isinstance(v, dict) and "tpu" in str(v.get("detail", {}).get("platform", ""))
            for v in prior.values()
        ):
            out = os.path.join(ROOT, "BENCHES.cpu-fallback.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
        f.write("\n")
    print(f"wrote {out}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
