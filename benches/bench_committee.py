"""Big-committee vote plane bench (round 16): LIVE consensus at
100-400 validators, batched vs per-vote signature verification. Writes
BENCH_r16.json.

Three row families:

- consensus N=...    — a REAL ConsensusState (full receive routine, WAL,
                       block store) driven by an in-process committee:
                       N-1 stub validators whose proposals (when the
                       rotation elects them) and prevotes/precommits are
                       signed and injected through the peer queue — the
                       make_cs_and_stubs/Localnet scaffolding at
                       committee scale. Every height must collect +2/3
                       of N equal-power votes, so the receive routine
                       verifies ~2N gossiped signatures per height.
                       Each N runs twice: `batched` (the round-16
                       VoteBatcher — one verify_batch_async gateway call
                       per drained (height,round,type) group) vs
                       `per_vote` (vote_batching=False: the seed plane's
                       one-verify-per-vote receive path). The chains are
                       asserted BYTE-IDENTICAL per height (block hash,
                       part-set root, app hash) — batching changes WHEN
                       signatures verify, never what commits — and
                       batched blocks/s >= 1.3x per-vote is ASSERTED at
                       N=100 (the acceptance bar; measured ~2-3x on this
                       box, diluted by the pump's own pure-python vote
                       SIGNING which both modes pay identically).
- commit_verify N=...— verify_commit latency on an N-validator commit:
                       per-signature pure loop vs ONE gateway batch
                       (native AVX on the CPU floor, streamed devd when
                       a daemon serves — the live row joins the standard
                       tunnel-window queue).
- aggregate N=...    — the aggregate-commit format (types/agg_commit;
                       the round-22 cutover's wire object,
                       docs/upgrade.md): wire bytes of the full Commit
                       vs the half-aggregated object (asserted < 0.6x
                       at every N; ~0.22x at 400), a verification
                       round trip, and the round-22 verify-latency A/B
                       (`verify_s` gateway-batched vs
                       `verify_python_s` pure reference vs
                       `full_per_sig_s` — the per-sig loop the cutover
                       retires).

Chip-free by construction on this box; the consensus and commit-verify
batched rows ride whatever the gateway resolves (devd rows auto-join
when a daemon serves). Run from the repo root:
python benches/bench_committee.py  (BENCH_COMMITTEE_SMOKE=1 for the
~30 s tier-1 gate: N=100 consensus A/B + the 4/100 object rows).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_COMMITTEE_SMOKE", "") == "1"
CONSENSUS_VALS = (
    [100] if SMOKE
    else [int(x) for x in os.environ.get(
        "BENCH_COMMITTEE_VALS", "4,32,100,400").split(",")]
)
OBJECT_VALS = [4, 100] if SMOKE else [4, 32, 100, 400]
N_HEIGHTS = int(os.environ.get("BENCH_COMMITTEE_HEIGHTS", "3"))
MIN_RATIO = float(os.environ.get("BENCH_COMMITTEE_MIN_RATIO", "1.3"))
ASSERT_AT = int(os.environ.get("BENCH_COMMITTEE_ASSERT_VALS", "100"))
GENESIS_NS = 1_700_000_000_000_000_000
CHAIN_ID = "bench_committee"


def _committee(n):
    """n seeded validators, sorted in validator-set (address) order —
    identical across runs so chains can be asserted byte-identical."""
    from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidatorFS

    pvs = []
    for i in range(n):
        seed = (b"committee-%05d" % i).ljust(32, b"\x00")
        pvs.append(PrivValidatorFS(gen_priv_key_ed25519(seed), None))
    pvs.sort(key=lambda pv: pv.get_address())
    doc = GenesisDoc(
        genesis_time_ns=GENESIS_NS,
        chain_id=CHAIN_ID,
        validators=[
            GenesisValidator(pv.get_pub_key(), 1, f"v{i}")
            for i, pv in enumerate(pvs)
        ],
    )
    return doc, pvs


def _build_cs(doc, pvs):
    """A real ConsensusState over MemDB, operated by the height-1
    proposer's key; liveness timeouts generous (the pump is prompt, and
    a stray round bump would fork the byte-identity assert)."""
    import tempfile

    from tendermint_tpu.abci.apps.kvstore import KVStoreApp
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.config import test_config
    from tendermint_tpu.consensus.state import ConsensusState
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.libs.events import EventSwitch
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.proxy.app_conn import AppConnConsensus, AppConnMempool
    from tendermint_tpu.state.state import State

    state = State.get_state(MemDB(), doc)
    proposer = state.validators.get_proposer()
    own_pv = next(pv for pv in pvs if pv.get_address() == proposer.address)
    app = KVStoreApp()
    mtx = threading.RLock()
    mp = Mempool(test_config().mempool, AppConnMempool(LocalClient(app, mtx)))
    cfg = test_config().consensus
    cfg.root_dir = tempfile.mkdtemp(prefix="bench-committee-")
    cfg.timeout_commit = 0.05
    cfg.skip_timeout_commit = True
    cfg.timeout_propose = 60.0
    cfg.timeout_prevote = 60.0
    cfg.timeout_precommit = 60.0
    evsw = EventSwitch()
    evsw.start()
    cs = ConsensusState(
        cfg, state, AppConnConsensus(LocalClient(app, mtx)),
        BlockStore(MemDB()), mp,
    )
    cs.set_event_switch(evsw)
    cs.set_priv_validator(own_pv)
    # the A/B isolates the VOTE plane: the deferred-apply pipeline is off
    # in both modes (empty blocks apply in microseconds), and block times
    # are pinned so chains are reproducible byte-for-byte
    cs.pipeline_apply = False
    cs.propose_time_source = lambda h: GENESIS_NS + h * 1_000_000_000
    return cs, own_pv


def _wait(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.002)
    raise SystemExit(f"committee pump stalled waiting for {what}")


def _pump(cs, pvs, own_pv, heights):
    """The committee: for every height, propose (when the rotation
    elects a stub), then inject every stub's prevote and precommit —
    the full +2/3 formation path a real 100-400 node net exercises,
    minus the sockets."""
    from tendermint_tpu.consensus import messages as msgs
    from tendermint_tpu.consensus.round_state import RoundStep
    from tendermint_tpu.types import BlockID, Proposal, Vote
    from tendermint_tpu.types.block import Block, empty_commit
    from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE

    by_addr = {pv.get_address(): pv for pv in pvs}
    own_addr = own_pv.get_address()
    for h in range(1, heights + 1):
        # last_commit.has_all(): every straggler precommit of h-1 must be
        # absorbed before ANY height-h proposal reads make_commit() — the
        # byte-identity contract (a partial commit snapshot is exactly
        # the timing artifact the A/B must not measure)
        _wait(
            lambda: cs.rs.height == h
            and cs.state.last_block_height == h - 1
            and (h == 1 or (cs.rs.last_commit is not None
                            and cs.rs.last_commit.has_all())),
            60, f"height {h}",
        )
        proposer = cs.rs.validators.get_proposer()
        if proposer.address != own_addr:
            # the elected stub proposes: build the exact block the real
            # node would (pinned time, empty txs, the full last commit)
            commit = (
                empty_commit() if h == 1 else cs.rs.last_commit.make_commit()
            )
            block, parts = Block.make_block(
                height=h,
                chain_id=CHAIN_ID,
                txs=[],
                commit=commit,
                prev_block_id=cs.state.last_block_id,
                val_hash=cs.state.validators.hash(),
                app_hash=cs.state.app_hash,
                part_size=cs.state.params().block_gossip.block_part_size_bytes,
                time_ns=GENESIS_NS + h * 1_000_000_000,
            )
            proposal = by_addr[proposer.address].sign_proposal(
                CHAIN_ID, Proposal(h, 0, parts.header())
            )
            cs.set_proposal_msg(proposal, peer_id="pump")
            for i in range(parts.total):
                cs.add_peer_message(
                    msgs.BlockPartMessage(h, 0, parts.get_part(i)), "pump"
                )
        _wait(
            lambda: cs.rs.height == h and cs.rs.proposal_block is not None,
            60, f"proposal at {h}",
        )
        bid = BlockID(
            cs.rs.proposal_block.hash(), cs.rs.proposal_block_parts.header()
        )
        for type_ in (VOTE_TYPE_PREVOTE, VOTE_TYPE_PRECOMMIT):
            votes = []
            for i, pv in enumerate(pvs):
                if pv.get_address() == own_addr:
                    continue  # cs signs its own
                v = Vote(
                    validator_address=pv.get_address(),
                    validator_index=i,
                    height=h,
                    round_=0,
                    type_=type_,
                    block_id=bid,
                )
                votes.append(pv.sign_vote(CHAIN_ID, v))
            for v in votes:
                cs.add_vote_msg(v, peer_id="pump")
            if type_ == VOTE_TYPE_PREVOTE:
                # cs must lock + precommit before the precommit wave so
                # every height commits at round 0 in both modes
                _wait(
                    lambda: cs.rs.height > h
                    or (cs.rs.step >= RoundStep.PRECOMMIT),
                    60, f"precommit step at {h}",
                )
    _wait(lambda: cs.rs.height > heights, 60, "final commit")


def _run_consensus(n, batched):
    doc, pvs = _committee(n)
    cs, own_pv = _build_cs(doc, pvs)
    cs.vote_batching = batched
    pump_exc = []

    def pump():
        try:
            _pump(cs, pvs, own_pv, N_HEIGHTS)
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            pump_exc.append(exc)

    t = threading.Thread(target=pump, daemon=True)
    t0 = time.perf_counter()
    cs.start()
    t.start()
    t.join(timeout=120 + 10 * N_HEIGHTS)
    wall_s = time.perf_counter() - t0
    alive = t.is_alive()
    cs.stop()
    if pump_exc:
        raise SystemExit(f"committee pump failed: {pump_exc[0]}")
    if alive:
        raise SystemExit(f"committee run (n={n}) never finished")
    fps = {}
    for h in range(1, N_HEIGHTS + 1):
        meta = cs.block_store.load_block_meta(h)
        block = cs.block_store.load_block(h)
        fps[h] = (
            meta.block_id.hash.hex(),
            meta.block_id.parts_header.hash.hex(),
            block.header.app_hash.hex(),
        )
    row = {
        "row": f"consensus_n{n}_{'batched' if batched else 'per_vote'}",
        "validators": n,
        "heights": N_HEIGHTS,
        "wall_s": round(wall_s, 3),
        "blocks_per_sec": round(N_HEIGHTS / wall_s, 3),
        "vote_batches": cs.vote_batcher.batches,
        "vote_batched_sigs": cs.vote_batcher.batched_sigs,
        "vote_singletons": cs.vote_batcher.singletons,
        "platform": "host",
    }
    return row, fps


def _signed_commit(n, height=7):
    from tendermint_tpu.types.block import Commit
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote

    doc, pvs = _committee(n)
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.state.state import State

    vals = State.get_state(MemDB(), doc).validators
    bid = BlockID(b"\x17" * 20, PartSetHeader(1, b"\x29" * 20))
    pres = []
    for i, pv in enumerate(pvs):
        v = Vote(pv.get_address(), i, height, 0, VOTE_TYPE_PRECOMMIT, bid)
        pres.append(pv.sign_vote(CHAIN_ID, v))
    return vals, bid, Commit(bid, pres), height


def _commit_verify_rows():
    from tendermint_tpu.ops import gateway

    verifier = gateway.Verifier(min_tpu_batch=4)
    platform = "devd" if verifier._kernel == "devd" else "host"
    rows = []
    for n in OBJECT_VALS:
        vals, bid, commit, height = _signed_commit(n)
        t0 = time.perf_counter()
        vals.verify_commit(CHAIN_ID, bid, height, commit)  # per-sig pure loop
        per_sig_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        vals.verify_commit(
            CHAIN_ID, bid, height, commit,
            batch_verifier=verifier.commit_batch_verifier(),
        )
        batched_s = time.perf_counter() - t0
        rows.append({
            "row": f"commit_verify_n{n}",
            "validators": n,
            "per_sig_s": round(per_sig_s, 4),
            "batched_s": round(batched_s, 4),
            "vs_per_sig": round(per_sig_s / batched_s, 2) if batched_s else 0.0,
            "platform": platform,
        })
    return rows


def _aggregate_rows():
    from tendermint_tpu.crypto import ed25519_agg
    from tendermint_tpu.types.agg_commit import AggregateCommit

    rows = []
    for n in OBJECT_VALS:
        vals, bid, commit, height = _signed_commit(n)
        t0 = time.perf_counter()
        agg = AggregateCommit.from_commit(commit, CHAIN_ID, vals)
        agg_build_s = time.perf_counter() - t0
        # round 22: the verify-latency A/B the cutover rides — the same
        # aggregate through the gateway-batched dual-scalar-mul path
        # (devd/sharded/direct kernel, CPU floor included) vs the
        # pure-python reference, next to the full commit's per-sig loop
        t0 = time.perf_counter()
        agg.verify(CHAIN_ID, vals)  # gateway-batched (default verifier)
        agg_verify_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        agg.verify(CHAIN_ID, vals,
                   agg_verifier=ed25519_agg.verify_aggregate)
        agg_verify_py_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        vals.verify_commit(CHAIN_ID, bid, height, commit)
        full_per_sig_s = time.perf_counter() - t0
        commit_bytes = len(commit.to_bytes())
        agg_bytes = len(agg.to_bytes())
        ratio = agg_bytes / commit_bytes
        assert ratio < 0.6, (
            f"aggregate commit only {ratio:.2f}x full at n={n} "
            "(expected < 0.6x)"
        )
        # wire round trip must still verify
        AggregateCommit.from_bytes(agg.to_bytes()).verify(CHAIN_ID, vals)
        rows.append({
            "row": f"aggregate_n{n}",
            "validators": n,
            "commit_bytes": commit_bytes,
            "aggregate_bytes": agg_bytes,
            "bytes_vs_full": round(ratio, 3),
            "aggregate_s": round(agg_build_s, 4),
            "verify_s": round(agg_verify_s, 4),
            "verify_python_s": round(agg_verify_py_s, 4),
            "full_per_sig_s": round(full_per_sig_s, 4),
            "verify_vs_per_sig": round(full_per_sig_s / agg_verify_s, 2)
            if agg_verify_s else 0.0,
            "platform": "host",
        })
    return rows


def main() -> None:
    os.environ.setdefault("TENDERMINT_TPU_PLATFORM", "cpu")
    rows = []
    ratios = {}
    for n in CONSENSUS_VALS:
        per_row, per_fps = _run_consensus(n, batched=False)
        bat_row, bat_fps = _run_consensus(n, batched=True)
        assert bat_fps == per_fps, (
            f"batched chain diverged from per-vote at n={n}: "
            f"{bat_fps} vs {per_fps}"
        )
        assert bat_row["vote_batches"] >= 1, "batched run never batched"
        assert per_row["vote_batches"] == 0, "per-vote run dispatched a batch"
        ratio = bat_row["blocks_per_sec"] / per_row["blocks_per_sec"]
        ratios[n] = ratio
        rows.extend([per_row, bat_row, {
            "row": f"consensus_n{n}_batched_vs_per_vote",
            "validators": n,
            "ratio": round(ratio, 3),
            "byte_identity": "block hash + part-set root + app hash, "
                             "all heights, both modes",
        }])
        print(f"  n={n}: per-vote {per_row['blocks_per_sec']} blk/s, "
              f"batched {bat_row['blocks_per_sec']} blk/s ({ratio:.2f}x)",
              file=sys.stderr)
    if ASSERT_AT in ratios:
        assert ratios[ASSERT_AT] >= MIN_RATIO, (
            f"batched vote verify only {ratios[ASSERT_AT]:.2f}x per-vote at "
            f"{ASSERT_AT} validators (floor {MIN_RATIO}x)"
        )
    rows.extend(_commit_verify_rows())
    rows.extend(_aggregate_rows())

    out = {
        "bench": "committee",
        "smoke": SMOKE,
        "heights": N_HEIGHTS,
        "min_ratio_asserted": MIN_RATIO,
        "assert_at_validators": ASSERT_AT,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "rows": rows,
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r16.json"), "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    summary = {
        "config": "16_committee",
        "ratio_at_assert": round(ratios.get(ASSERT_AT, 0.0), 3),
        "agg_bytes_vs_full": next(
            (r["bytes_vs_full"] for r in rows
             if r["row"] == f"aggregate_n{OBJECT_VALS[-1]}"), None
        ),
        "detail": {"rows": len(rows), "smoke": SMOKE},
    }
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
