"""RPC/WebSocket load generator against a LIVE node (reference:
benchmarks/simu/counter.go — a WS client firing broadcast_tx frames at a
running node and draining the response stream).

Boots a real `tendermint_tpu.cli node` process (kvstore, ephemeral home),
opens the /websocket endpoint, streams BENCH_RPC_TXS broadcast_tx_async
frames while a drain thread counts acceptances, and measures:
- accepted tx/s through the full RPC + mempool ingress path,
- block/commit progress while under load (the node must keep committing),
- round 11: Prometheus scrape cost — GET /metrics hammered concurrently
  with the load (latency p50/max, >= 40 families, one consensus_trace
  pulled, consensus height_seconds not moved by the scrapes; the row
  merges into BENCH_r11.json beside bench_telemetry's sections).

Prints ONE JSON line like the other benches. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_TXS = int(os.environ.get("BENCH_RPC_TXS", "5000"))
RPC_PORT = int(os.environ.get("BENCH_RPC_PORT", "47321"))
N_SCRAPES = int(os.environ.get("BENCH_RPC_SCRAPES", "100"))
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scrape(port: int) -> tuple[float, int]:
    """(seconds, family count) for one GET /metrics Prometheus scrape."""
    t0 = time.perf_counter()
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as r:
        assert r.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4"
        ), r.headers["Content-Type"]
        text = r.read().decode()
    dt = time.perf_counter() - t0
    fams = sum(1 for l in text.splitlines() if l.startswith("# TYPE "))
    return dt, fams


def _status(port: int) -> dict | None:
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps({"method": "status", "params": {}, "id": 1}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=2) as r:
            return json.loads(r.read().decode())["result"]
    except Exception:  # noqa: BLE001 — node not up yet
        return None


def main() -> int:
    home = tempfile.mkdtemp(prefix="bench-rpc-")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TENDERMINT_TPU_PLATFORM": os.environ.get("TENDERMINT_TPU_PLATFORM", "cpu"),
    }
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home,
         "init", "--chain-id", "rpc-load"],
        check=True, capture_output=True, env=env,
    )
    node = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node",
         "--proxy_app", "kvstore",
         "--rpc.laddr", f"tcp://127.0.0.1:{RPC_PORT}",
         "--p2p.laddr", "tcp://127.0.0.1:0", "--log_level", "error"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.time() + 60
        st = None
        while time.time() < deadline:
            st = _status(RPC_PORT)
            if st and int(st["latest_block_height"]) >= 1:
                break
            time.sleep(0.5)
        assert st, "node never served /status"
        start_height = int(st["latest_block_height"])

        from tendermint_tpu.rpc.client import WSClient

        ws = WSClient(f"127.0.0.1:{RPC_PORT}")
        accepted = {"n": 0, "err": 0}
        done = threading.Event()

        def drain():
            while accepted["n"] + accepted["err"] < N_TXS:
                try:
                    msg = ws.responses.get(timeout=30)
                except Exception:  # noqa: BLE001 — stalled stream ends the bench
                    break
                if msg.get("error"):
                    accepted["err"] += 1
                else:
                    accepted["n"] += 1
            done.set()

        th = threading.Thread(target=drain, daemon=True)
        th.start()

        # scrape-cost row (round 11): a Prometheus scraper hammers GET
        # /metrics WHILE the broadcast load runs — a scrape must be an
        # O(gauges) render, never something that stalls consensus or the
        # ingress path. Latencies recorded; liveness judged below.
        scrape_times: list[float] = []
        scrape_fams = {"n": 0}
        scrape_errs = {"n": 0}
        scrape_stop = threading.Event()

        def scraper():
            while not scrape_stop.is_set() and len(scrape_times) < N_SCRAPES:
                try:
                    dt, fams = _scrape(RPC_PORT)
                    scrape_times.append(dt)
                    scrape_fams["n"] = fams
                except Exception:  # noqa: BLE001 — counted, judged after
                    scrape_errs["n"] += 1
                time.sleep(0.02)

        scraper_th = threading.Thread(target=scraper, daemon=True)
        scraper_th.start()

        t0 = time.perf_counter()
        for i in range(N_TXS):
            tx = b"load-%06d=v" % i
            ws._send_frame(0x1, json.dumps({
                "jsonrpc": "2.0", "id": i + 1,
                "method": "broadcast_tx_async", "params": {"tx": tx.hex()},
            }).encode())
        assert done.wait(300), "response drain stalled"
        elapsed = time.perf_counter() - t0
        # finish the scrape quota against the still-running node, then
        # read the liveness gauges the scrape must not have moved
        scraper_th.join(timeout=60)
        scrape_stop.set()
        # liveness: the flooded txs must land in blocks — on a 1-core box
        # the burst can starve consensus DURING the load window, so allow
        # a post-load commit window before judging
        commit_deadline = time.time() + 60
        blocks = 0
        while time.time() < commit_deadline:
            end_st = _status(RPC_PORT)
            if end_st:
                blocks = int(end_st["latest_block_height"]) - start_height
                if blocks > 0:
                    break
            time.sleep(1.0)
        # scrape row judgment: every scrape answered, the family bar
        # held, one consensus_trace pulls, and consensus liveness did
        # not degrade under the scrape+broadcast overlap (a scrape that
        # stalled the receive routine would blow height_seconds_max out
        # to the stall length — tens of seconds, not this bound)
        assert scrape_errs["n"] == 0, f"{scrape_errs['n']} scrapes failed"
        assert len(scrape_times) >= min(N_SCRAPES, 20), len(scrape_times)
        assert scrape_fams["n"] >= 40, f"{scrape_fams['n']} families"
        ordered = sorted(scrape_times)
        scrape_p50 = ordered[len(ordered) // 2]
        scrape_max = ordered[-1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{RPC_PORT}/",
            data=json.dumps({"method": "metrics", "params": {},
                             "id": 9}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            m = json.loads(r.read().decode())["result"]
        assert m["consensus_height_seconds_max"] < 15.0, (
            "consensus stalled under scrape load: "
            f"height_seconds_max={m['consensus_height_seconds_max']}"
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{RPC_PORT}/",
            data=json.dumps({"method": "consensus_trace",
                             "params": {"last": 1}, "id": 10}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            traces = json.loads(r.read().decode())["result"]["traces"]
        assert traces and traces[0]["segments"], "no trace under load"
        scrape_row = {
            "scrapes": len(scrape_times),
            "families": scrape_fams["n"],
            "scrape_ms_p50": round(scrape_p50 * 1000, 2),
            "scrape_ms_max": round(scrape_max * 1000, 2),
            "height_seconds_last": m["consensus_height_seconds_last"],
            "height_seconds_max": m["consensus_height_seconds_max"],
            "blocks_committed_during_load": blocks,
            "note": (
                "GET /metrics hammered concurrently with the ws "
                "broadcast burst; height_seconds_max < 15s asserted "
                "(a scrape-induced stall would dwarf it)"
            ),
        }
        ws.close()

        assert accepted["err"] == 0, f"{accepted['err']} tx rejected"
        assert blocks > 0, "node stopped committing under RPC load"
        # merge into BENCH_r11.json beside bench_telemetry's sections
        record_path = os.path.join(ROOT, "BENCH_r11.json")
        try:
            with open(record_path) as f:
                record = json.load(f)
        except (OSError, ValueError):
            record = {}
        record["rpc_scrape"] = scrape_row
        record.setdefault("metric", "telemetry plane: scrape cost")
        with open(record_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(json.dumps({
            "metric": "rpc_ws_broadcast_tx_per_sec",
            "value": round(N_TXS / elapsed, 1),
            "unit": "txs/s",
            "vs_baseline": 1.0,  # host-path bench: no reference numbers exist
            "detail": {
                "txs": N_TXS,
                "elapsed_s": round(elapsed, 3),
                "blocks_committed_during_load": blocks,
                "transport": "websocket (RFC6455, JSON-RPC frames)",
                "app": "kvstore(local)",
                "scrape": scrape_row,
            },
        }))
        return 0
    finally:
        node.terminate()
        try:
            node.wait(timeout=10)
        except subprocess.TimeoutExpired:
            node.kill()


if __name__ == "__main__":
    sys.exit(main())
