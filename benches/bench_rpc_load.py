"""RPC/WebSocket load generator against a LIVE node (reference:
benchmarks/simu/counter.go — a WS client firing broadcast_tx frames at a
running node and draining the response stream).

Boots a real `tendermint_tpu.cli node` process (kvstore, ephemeral home),
opens the /websocket endpoint, streams BENCH_RPC_TXS broadcast_tx_async
frames while a drain thread counts acceptances, and measures:
- accepted tx/s through the full RPC + mempool ingress path,
- block/commit progress while under load (the node must keep committing).

Prints ONE JSON line like the other benches. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_TXS = int(os.environ.get("BENCH_RPC_TXS", "5000"))
RPC_PORT = int(os.environ.get("BENCH_RPC_PORT", "47321"))


def _status(port: int) -> dict | None:
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps({"method": "status", "params": {}, "id": 1}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=2) as r:
            return json.loads(r.read().decode())["result"]
    except Exception:  # noqa: BLE001 — node not up yet
        return None


def main() -> int:
    home = tempfile.mkdtemp(prefix="bench-rpc-")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TENDERMINT_TPU_PLATFORM": os.environ.get("TENDERMINT_TPU_PLATFORM", "cpu"),
    }
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home,
         "init", "--chain-id", "rpc-load"],
        check=True, capture_output=True, env=env,
    )
    node = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node",
         "--proxy_app", "kvstore",
         "--rpc.laddr", f"tcp://127.0.0.1:{RPC_PORT}",
         "--p2p.laddr", "tcp://127.0.0.1:0", "--log_level", "error"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.time() + 60
        st = None
        while time.time() < deadline:
            st = _status(RPC_PORT)
            if st and int(st["latest_block_height"]) >= 1:
                break
            time.sleep(0.5)
        assert st, "node never served /status"
        start_height = int(st["latest_block_height"])

        from tendermint_tpu.rpc.client import WSClient

        ws = WSClient(f"127.0.0.1:{RPC_PORT}")
        accepted = {"n": 0, "err": 0}
        done = threading.Event()

        def drain():
            while accepted["n"] + accepted["err"] < N_TXS:
                try:
                    msg = ws.responses.get(timeout=30)
                except Exception:  # noqa: BLE001 — stalled stream ends the bench
                    break
                if msg.get("error"):
                    accepted["err"] += 1
                else:
                    accepted["n"] += 1
            done.set()

        th = threading.Thread(target=drain, daemon=True)
        th.start()

        t0 = time.perf_counter()
        for i in range(N_TXS):
            tx = b"load-%06d=v" % i
            ws._send_frame(0x1, json.dumps({
                "jsonrpc": "2.0", "id": i + 1,
                "method": "broadcast_tx_async", "params": {"tx": tx.hex()},
            }).encode())
        assert done.wait(300), "response drain stalled"
        elapsed = time.perf_counter() - t0
        # liveness: the flooded txs must land in blocks — on a 1-core box
        # the burst can starve consensus DURING the load window, so allow
        # a post-load commit window before judging
        commit_deadline = time.time() + 60
        blocks = 0
        while time.time() < commit_deadline:
            end_st = _status(RPC_PORT)
            if end_st:
                blocks = int(end_st["latest_block_height"]) - start_height
                if blocks > 0:
                    break
            time.sleep(1.0)
        ws.close()

        assert accepted["err"] == 0, f"{accepted['err']} tx rejected"
        assert blocks > 0, "node stopped committing under RPC load"
        print(json.dumps({
            "metric": "rpc_ws_broadcast_tx_per_sec",
            "value": round(N_TXS / elapsed, 1),
            "unit": "txs/s",
            "vs_baseline": 1.0,  # host-path bench: no reference numbers exist
            "detail": {
                "txs": N_TXS,
                "elapsed_s": round(elapsed, 3),
                "blocks_committed_during_load": blocks,
                "transport": "websocket (RFC6455, JSON-RPC frames)",
                "app": "kvstore(local)",
            },
        }))
        return 0
    finally:
        node.terminate()
        try:
            node.wait(timeout=10)
        except subprocess.TimeoutExpired:
            node.kill()


if __name__ == "__main__":
    sys.exit(main())
