"""Overload-control bench (round 23): consensus cadence under ingress
flood, scrape-visible shed ratios, and priority-vs-bulk commit ordering
(docs/serving.md).

Runs the `overload` ops/localnet scenario: a real 4-node process fleet
where node 0 is flooded with bulk writes (4 clients pinned to one
throttled source IP), hot status reads (4 clients on a second IP), and
two deliberately-slow WS subscribers — while the scenario asserts

- consensus cadence stays within 1.5x the unloaded baseline,
- every shed is visible on the scrape surface (rpc_shed_total,
  mempool_lane_full_total, ws_evictions_total),
- a priority probe tx commits at a strictly LOWER height than a bulk
  marker submitted BEFORE it (the mempool lane proof),
- the load-shed ladder transition landed in the flight ring, and
- per-height byte identity holds across the fleet (lanes reorder only
  within a block's reap, never across nodes).

Rows: cadence ratio (flood/baseline heights/s), shed counts by plane,
probe-vs-marker heights, WS evictions/drops, flood HTTP status tallies.

BENCH_OVERLOAD_SMOKE=1 shrinks to one 4-node run (~90 s) for the
tier-1 gate (`make overload-smoke`). Prints ONE JSON line like the
other benches; writes BENCH_r23.json on full runs. Run from the repo
root: python benches/bench_overload.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_OVERLOAD_SMOKE", "") == "1"
# (n, baseline heights) per run; the flood window is max(heights, 8)
# blocks inside the scenario
SCALES = [(4, 3)] if SMOKE else [(4, 5), (6, 4)]


def main() -> None:
    os.environ.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
    os.environ.setdefault("TENDERMINT_TPU_PLATFORM", "cpu")

    from tendermint_tpu.ops.localnet import LocalnetSpec, run_scenario

    rows = []
    port = 47700
    for n, heights in SCALES:
        root = tempfile.mkdtemp(prefix=f"bench-overload-{n}-")
        spec = LocalnetSpec(n=n, root=root, seed=23, base_port=port)
        port += 2 * n + 10
        t0 = time.perf_counter()
        r = run_scenario(spec, "overload", heights=heights)
        wall = time.perf_counter() - t0
        # the scenario already asserted the cadence floor, the shed
        # visibility, the probe ordering, and byte identity — the bench
        # records the measurables
        rows.append({
            "mode": f"overload:n={n}",
            "nodes": n,
            "baseline_heights_per_s": r["baseline_heights_per_s"],
            "flood_heights_per_s": r["flood_heights_per_s"],
            "cadence_ratio": r["cadence_ratio"],
            "probe_height": r["probe_height"],
            "marker_height": r["marker_height"],
            "priority_blocks_ahead": r["marker_height"] - r["probe_height"],
            "rpc_sheds": r["rpc_sheds"],
            "lane_full_rejects": r["lane_full_rejects"],
            "shed_writes_rejects": r["shed_writes_rejects"],
            "ws_evictions": r["ws_evictions"],
            "ws_dropped_events": r["ws_dropped_events"],
            "overload_transitions": r["overload_transitions"],
            "flood_statuses": r["flood_statuses"],
            "converged_heights": r["converged_heights"],
            "wall_s": round(wall, 1),
        })

    record = {
        "bench": "overload",
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": "cpu",
        "smoke": SMOKE,
        "cores": os.cpu_count(),
        "rows": rows,
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r23.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
