"""Codec / primitive micro-benchmarks (reference: benchmarks/codec_test.go,
chan_test.go, map_test.go, os_test.go, atomic_test.go).

The reference's micro set times its hot primitives: status/NodeInfo
encoding over the wire codec, map churn with address-like string keys,
channel make/close, and small appending file writes. Same shapes here
against OUR primitives — the JSON-RPC status payload, the binary codec
(codec/binary.py), canonical JSON sign-bytes, NodeInfo JSON, dict churn,
queue.Queue make/close (the CList/queue analogue), and autofile group
writes — so codec or runtime regressions show up as numbers, not
anecdotes.

Prints ONE JSON line like the other benches. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import queue
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("BENCH_MICRO_N", "20000"))


def _rate(fn, n=N) -> float:
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return n / (time.perf_counter() - t0)


def main() -> None:
    from tendermint_tpu.codec.binary import Encoder
    from tendermint_tpu.codec.canonical import canonical_dumps
    from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
    from tendermint_tpu.libs.autofile import Group
    from tendermint_tpu.p2p.node_info import NodeInfo, default_version

    pv = gen_priv_key_ed25519(b"\x11" * 32)
    info = NodeInfo(
        pub_key=pv.pub_key(),
        moniker="micro-bench",
        network="bench-chain",
        version=default_version("bench"),
        listen_addr="127.0.0.1:46656",
    )

    # status payload a node serves per /status call (codec_test.go:14-38)
    status = {
        "node_info": info.to_json(),
        "latest_block_height": 123456,
        "latest_block_hash": "ab" * 20,
        "latest_app_hash": "cd" * 20,
        "latest_block_time": 1_700_000_000_000,
    }

    def enc_status_json():
        json.dumps(status, sort_keys=True)

    def enc_node_info_json():
        json.dumps(info.to_json(), sort_keys=True)

    def enc_node_info_binary():
        e = Encoder()
        e.write_string(info.moniker)
        e.write_string(info.network)
        e.write_bytes(info.pub_key.raw)
        e.write_string(info.listen_addr or "")
        e.buf()

    vote_canonical = {
        "chain_id": "bench-chain",
        "vote": {"block_id": {}, "height": 1, "round": 0, "type": 2},
    }

    def enc_canonical_sign_bytes():
        canonical_dumps(vote_canonical)

    # map churn with hex-address keys (map_test.go)
    addrs = [("%040x" % i) for i in range(256)]

    def map_churn():
        m: dict = {}
        for a in addrs:
            m[a] = 1
        for a in addrs:
            m[a]

    # queue make/close — the Go chan make/close analogue (chan_test.go)
    def queue_make():
        queue.Queue(maxsize=1)

    results = {
        "encode_status_json_per_sec": round(_rate(enc_status_json), 1),
        "encode_node_info_json_per_sec": round(_rate(enc_node_info_json), 1),
        "encode_node_info_binary_per_sec": round(_rate(enc_node_info_binary), 1),
        "encode_canonical_vote_per_sec": round(_rate(enc_canonical_sign_bytes), 1),
        "map_churn_256_per_sec": round(_rate(map_churn, n=2000), 1),
        "queue_make_per_sec": round(_rate(queue_make), 1),
    }

    # small appending writes through the tx-WAL file group (os_test.go)
    d = tempfile.mkdtemp(prefix="bench-micro-")
    g = Group(os.path.join(d, "wal"))
    line = "ab" * 32

    def wal_write():
        g.write_line(line)

    results["wal_write_per_sec"] = round(_rate(wal_write, n=5000), 1)
    g.flush()
    g.close()

    print(
        json.dumps(
            {
                "metric": "micro_encode_status_per_sec",
                "value": results["encode_status_json_per_sec"],
                "unit": "ops/s",
                "vs_baseline": 1.0,  # host-path micro set: no reference numbers
                "detail": results,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
