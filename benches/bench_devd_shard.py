"""Round 21: sharded device plane — fleet scaling + kill-mid-burst chaos.

PR 21 teaches the gateway to schedule verify/hash work across N devd
daemons (TENDERMINT_DEVD_SOCKS) with work-stealing dispatch and
per-endpoint breakers. This bench is that claim, measured:

- scaling rows: aggregate verify sigs/s and streamed-hash MB/s through
  ops/devd_shard against 1 / 2 / 4 sim daemons. Each daemon is a
  separate PROCESS serving a fixed-rate sim device
  (TENDERMINT_DEVD_SIM_RATE), so device time is the constant and the
  dispatcher's fan-out is the variable. Asserted: >= MIN_SCALING (1.6x)
  aggregate sigs/s at 2 daemons vs 1.
- chaos row: SIGKILL one daemon of three while a burst is in flight.
  Asserted: every lane of every batch keeps its exact verdict (planted
  wrong-length forgeries stay invalid, the rest stay valid) through the
  re-dispatch; the dead endpoint's breaker opens (latency recorded),
  the plane stays up on the survivors, and after restart the breaker's
  half-open probe re-closes it (recovery latency recorded).

Digest parity is cross-checked against the host ripemd160 and across
fleet sizes (a 4-daemon plane must emit byte-identical digests to a
1-daemon plane).

BENCH_DEVD_SHARD_SMOKE=1 is the chip-free CI gate (~30 s): fleet sizes
[1, 2], smaller batches, the same scaling assert and a 2-daemon
kill-one failover row, no BENCH_r21.json rewrite. The full run writes
BENCH_r21.json at the repo root. Prints ONE JSON line either way. Run
from the repo root.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE = os.environ.get("BENCH_DEVD_SHARD_SMOKE", "0") == "1"
COUNTS = [1, 2] if SMOKE else [1, 2, 4]
N_SIGS = int(os.environ.get(
    "BENCH_SHARD_SIGS", "8192" if SMOKE else "16384"))
HASH_PARTS = int(os.environ.get(
    "BENCH_SHARD_PARTS", "192" if SMOKE else "512"))
PART_BYTES = int(os.environ.get("BENCH_SHARD_PART_BYTES", "65536"))
TRIALS = int(os.environ.get("BENCH_SHARD_TRIALS", "2" if SMOKE else "3"))
# per-daemon sim device rate: low enough that the device, not the
# host-side transport, is the bottleneck — so fleet scaling measures
# the dispatcher, not pickle throughput
SIM_RATE = float(os.environ.get("BENCH_SHARD_SIM_RATE", "30000"))
MIN_SCALING = float(os.environ.get("BENCH_SHARD_MIN_SCALING", "1.6"))
CHAOS_LANES = int(os.environ.get(
    "BENCH_SHARD_CHAOS_LANES", "2048" if SMOKE else "4096"))

SIM_ENV = {"TENDERMINT_DEVD_SIM_RATE": str(int(SIM_RATE))}


def _structural_items(n: int) -> list:
    """Well-formed (32-byte pk, 64-byte sig) lanes for the sim verifier
    (it checks structure only — real signatures would burn bench time on
    keygen without exercising anything extra)."""
    return [
        (bytes([i % 251]) * 32, b"shard-%06d" % i, bytes([i % 249]) * 64)
        for i in range(n)
    ]


def _point_at(socks: str) -> None:
    """Re-point the in-process device plane at a fleet: env + every
    cache/latch/breaker that remembers the previous sockets."""
    from tendermint_tpu import devd
    from tendermint_tpu.ops import devd_shard, gateway

    os.environ["TENDERMINT_DEVD_SOCKS"] = socks
    os.environ.pop("TENDERMINT_DEVD_SOCK", None)
    devd.bust_avail_cache()
    devd_shard.reset()
    gateway.reset_devd_breaker()


def _fleet_row(n: int) -> dict:
    """Aggregate verify sigs/s + hash MB/s through the sharded
    dispatcher against n sim daemons; returns the row + leaf digests
    (for cross-fleet parity)."""
    from tendermint_tpu.crypto.hashing import ripemd160
    from tendermint_tpu.ops import devd_shard
    from tendermint_tpu.ops.faults import DaemonFleet

    fleet = DaemonFleet(n, extra_env=dict(SIM_ENV)).start()
    try:
        _point_at(fleet.socks_env)
        items = _structural_items(N_SIGS)
        parts = [bytes([i % 253]) * PART_BYTES for i in range(HASH_PARTS)]

        devd_shard.verify_batch(items[:256])  # connection + import warm
        devd_shard.hash_batch(parts[:16])

        verify_best = hash_best = float("inf")
        digests: list = []
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            verdicts = devd_shard.verify_batch(items)
            verify_best = min(verify_best, time.perf_counter() - t0)
            assert all(verdicts), "well-formed lanes must all verify"
            t0 = time.perf_counter()
            digests = devd_shard.hash_batch(parts, mode="part")
            hash_best = min(hash_best, time.perf_counter() - t0)
        assert digests[0] == ripemd160(parts[0]), "digest parity vs host"

        eps = devd_shard.endpoint_stats()
        total_bytes = HASH_PARTS * PART_BYTES
        return {
            "daemons": n,
            "sim_device_sigs_per_sec": SIM_RATE,
            "verify_items": N_SIGS,
            "aggregate_sigs_per_sec": round(N_SIGS / verify_best, 1),
            "verify_ms": round(verify_best * 1000, 1),
            "hash_parts": HASH_PARTS,
            "part_bytes": PART_BYTES,
            "hash_mb_per_sec": round(total_bytes / hash_best / 1e6, 1),
            "hash_ms": round(hash_best * 1000, 1),
            "stolen_slices": sum(d["stolen_slices"] for d in eps.values()),
            "dispatched_slices": sum(
                d["dispatched_slices"] for d in eps.values()),
            "_digests": digests,
        }
    finally:
        fleet.stop()


def _chaos_row(n: int) -> dict:
    """SIGKILL daemon 0 of n while a burst is in flight: every lane of
    every batch must keep its exact verdict through the re-dispatch;
    the dead endpoint's breaker opens and, after restart, re-closes."""
    from tendermint_tpu.ops import devd_shard, gateway
    from tendermint_tpu.ops.faults import DaemonFleet

    fleet = DaemonFleet(n, extra_env=dict(SIM_ENV)).start()
    try:
        _point_at(fleet.socks_env)
        # wrong-LENGTH forgeries (truncated sigs): the sim verifier is
        # structural, so these are its invalid lanes — and the host
        # ed25519 floor agrees. The streamed transport REJECTS malformed
        # lanes instead of returning verdicts, so pin this row to the
        # single-shot op.
        os.environ["TENDERMINT_DEVD_STREAM_MIN"] = "1000000"
        items = _structural_items(CHAOS_LANES)
        forged = sorted({13, CHAOS_LANES // 3, CHAOS_LANES - 1})
        for i in forged:
            pk, msg, sig = items[i]
            items[i] = (pk, msg, sig[:10])
        expected = [i not in forged for i in range(CHAOS_LANES)]
        dead = fleet.sock_paths[0]

        assert devd_shard.verify_batch(items) == expected  # pre-kill burst

        # kill mid-flight of the next batch
        killer = threading.Timer(0.02, fleet.kill, args=(0,))
        t_kill = time.perf_counter()
        killer.start()
        batches = 1
        open_latency = None
        for _ in range(10):
            assert devd_shard.verify_batch(items) == expected, (
                "per-lane verdicts diverged after SIGKILL mid-burst")
            batches += 1
            if open_latency is None and \
                    gateway.devd_breaker_states().get(dead) == 2:
                open_latency = time.perf_counter() - t_kill
        killer.join()
        assert open_latency is not None, "dead endpoint's breaker never opened"
        eps = devd_shard.endpoint_stats()
        assert eps[dead]["redispatches"] >= 1, eps
        assert devd_shard.plane_allow(), "survivors must keep the plane up"

        fleet.restart(0)
        t_up = time.perf_counter()
        recovery = None
        deadline = time.time() + 30.0
        while time.time() < deadline:
            # dispatch traffic drives the half-open probe on the
            # restarted socket; verdicts must hold throughout
            assert devd_shard.verify_batch(items) == expected
            batches += 1
            if gateway.devd_breaker_states().get(dead) == 0:
                recovery = time.perf_counter() - t_up
                break
            time.sleep(0.05)
        assert recovery is not None, "breaker never re-closed after restart"
        return {
            "mode": "kill-one-mid-burst",
            "daemons": n,
            "lanes_per_batch": CHAOS_LANES,
            "forged_lanes": forged,
            "batches_all_exact": batches,
            "breaker_open_latency_s": round(open_latency, 3),
            "breaker_recovery_latency_s": round(recovery, 3),
            "dead_endpoint_redispatches":
                devd_shard.endpoint_stats()[dead]["redispatches"],
        }
    finally:
        os.environ.pop("TENDERMINT_DEVD_STREAM_MIN", None)
        fleet.stop()


def main() -> None:
    # fast breaker windows so open/recovery latencies are bench-scale,
    # not production-scale (same idiom as bench_chaos)
    os.environ.setdefault("TENDERMINT_TPU_BREAKER_FAILURES", "2")
    os.environ.setdefault("TENDERMINT_TPU_BREAKER_BACKOFF_S", "0.1")
    os.environ.setdefault("TENDERMINT_TPU_BREAKER_BACKOFF_CAP_S", "1.0")

    rows = [_fleet_row(n) for n in COUNTS]
    base_digests = rows[0].pop("_digests")
    for row in rows[1:]:
        assert row.pop("_digests") == base_digests, (
            f"{row['daemons']}-daemon digests diverge from 1-daemon plane")

    chaos = _chaos_row(2 if SMOKE else 3)

    by_n = {r["daemons"]: r["aggregate_sigs_per_sec"] for r in rows}
    scaling_2v1 = round(by_n[2] / by_n[1], 3)

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": (
            "sharded device plane: aggregate sigs/s + hash MB/s vs fleet "
            "size; kill-one-mid-burst failover"
        ),
        "min_scaling_asserted": MIN_SCALING,
        "scaling_2v1": scaling_2v1,
        "rows": rows,
        "chaos": chaos,
        "note": (
            "sim daemons (fixed per-device sigs/s, separate processes) "
            "hold device time constant so fleet size is the variable; "
            "digests are byte-identical across fleet sizes; a live "
            "multi-chip window re-records with real daemons"
        ),
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r21.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "devd_shard_aggregate_sigs_per_sec",
        "value": by_n[max(by_n)],
        "unit": "sigs/s",
        "vs_baseline": scaling_2v1,  # 2-daemon aggregate vs 1-daemon
        "detail": {"rows": rows, "chaos": chaos, "smoke": SMOKE},
    }))

    assert scaling_2v1 >= MIN_SCALING, (
        f"2-daemon plane only {scaling_2v1}x a single daemon "
        f"(need >= {MIN_SCALING}x): {rows}"
    )


if __name__ == "__main__":
    sys.exit(main())
