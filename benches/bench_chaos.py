"""Chaos bench (round 8): recovery time and degraded-mode throughput of
the devd device plane under daemon kill/restart.

What production cares about when a chip (or its daemon) gets sick is not
just steady-state throughput but the shape of the degradation: how long
until the process notices and falls back (continuity), what the CPU
fallback sustains while the daemon is down (degraded delta), and how
long after the daemon returns until devd routing is restored (recovery
— the breaker's half-open probe closing). This bench measures all three
against a sim daemon (device time held constant, chip-free — same
methodology as bench_devd_stream.py) and writes BENCH_r08.json.

Rows:
- healthy:   streamed verify throughput, daemon serving (sigs/s)
- degraded:  throughput with the daemon SIGKILLed — the breaker-open CPU
             fallback path (sigs/s, + delta vs healthy)
- recovery:  median seconds from "daemon serving again" to "breaker
             re-closed AND a batch demonstrably devd-routed", over
             N_KILLS kill/restart cycles

Asserted floors (chip-free, so they gate `make chaos-smoke` in tier1):
- every batch during the whole run returns correct verdicts (continuity)
- recovery_s <= BENCH_CHAOS_MAX_RECOVERY_S (default 5 s with the bench's
  0.1 s/1 s breaker windows — generous; measured ~0.3-1.5 s)

BENCH_CHAOS_SMOKE=1 shrinks batches/cycles for the tier-1 gate.
Prints ONE JSON line like the other benches.
Run from the repo root: python benches/bench_chaos.py
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_CHAOS_SMOKE", "") == "1"
N_ITEMS = int(os.environ.get("BENCH_CHAOS_ITEMS", "2048" if SMOKE else "8192"))
N_KILLS = int(os.environ.get("BENCH_CHAOS_KILLS", "2" if SMOKE else "4"))
TRIALS = int(os.environ.get("BENCH_CHAOS_TRIALS", "3" if SMOKE else "5"))
SIM_RATE = float(os.environ.get("BENCH_CHAOS_SIM_RATE", "500000"))
MAX_RECOVERY_S = float(os.environ.get("BENCH_CHAOS_MAX_RECOVERY_S", "5.0"))


def _items(n: int) -> list:
    """REAL signed lanes, 256 distinct cycled to width: the degraded row
    runs the actual CPU verifier (structural fakes would rightly fail
    there), and the sim daemon structurally accepts the same lanes, so
    'all True' is the correct continuity invariant in every mode."""
    from tendermint_tpu.crypto import ed25519 as ed

    seeds = [bytes([8, k]) + b"\x08" * 30 for k in range(64)]
    base = []
    for i in range(min(n, 256)):
        seed = seeds[i % 64]
        msg = b"chaos-%06d" % i
        base.append((ed.public_key(seed), msg, ed.sign(seed, msg)))
    return [base[i % len(base)] for i in range(n)]


def _rate(verifier, items, trials: int) -> float:
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        oks = verifier.verify_batch(items)
        best = min(best, time.perf_counter() - t0)
        assert all(oks), "verdicts must stay correct in every mode"
    return len(items) / best


def main() -> None:
    # breaker windows for the bench: probe fast so RECOVERY measures the
    # plane, not a 30 s production backoff cap
    os.environ.setdefault("TENDERMINT_TPU_BREAKER_BACKOFF_S", "0.1")
    os.environ.setdefault("TENDERMINT_TPU_BREAKER_BACKOFF_CAP_S", "1.0")
    os.environ.setdefault("TENDERMINT_DEVD_STREAM_MIN", "64")
    sock = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"devd-chaos-{os.getpid()}.sock"
    )
    os.environ["TENDERMINT_DEVD_SOCK"] = sock
    os.environ["TENDERMINT_TPU_KERNEL"] = "devd"

    from tendermint_tpu import devd
    from tendermint_tpu.ops import gateway
    from tendermint_tpu.ops.faults import DaemonSupervisor, FaultPlan

    plan = FaultPlan(seed=8)
    sup = DaemonSupervisor(
        sock, {"TENDERMINT_DEVD_SIM_RATE": str(int(SIM_RATE))}, plan=plan
    )
    sup.start()
    items = _items(N_ITEMS)
    rows = []
    try:
        gateway.reset_devd_breaker()
        devd.bust_avail_cache()
        v = gateway.Verifier(min_tpu_batch=1)
        br = gateway.devd_breaker()

        healthy = _rate(v, items, TRIALS)
        assert v.stats()["tpu_sigs"] > 0, "healthy row must ride devd"
        rows.append({
            "mode": "healthy", "platform": "sim",
            "sigs_per_sec": round(healthy, 1),
            "sim_device_sigs_per_sec": SIM_RATE,
        })

        recoveries = []
        degraded = None
        for cycle in range(N_KILLS):
            sup.kill()
            # continuity: every batch during the outage answers correct
            # verdicts (first ones eat the failure triage, then the
            # breaker opens and the fallback serves clean)
            deadline = time.monotonic() + 30.0
            while br.state != br.OPEN:
                assert time.monotonic() < deadline, "breaker never opened"
                assert all(v.verify_batch(items))
            if degraded is None:
                degraded = _rate(v, items, TRIALS)
                rows.append({
                    "mode": "degraded", "platform": "cpu-fallback",
                    "sigs_per_sec": round(degraded, 1),
                    "delta_vs_healthy": round(degraded / healthy, 3),
                    "breaker": br.stats(),
                })
            sup.restart()  # blocks until the daemon holds again
            t0 = time.monotonic()
            before = v.stats()["tpu_sigs"]
            deadline = t0 + 30.0
            while True:
                assert time.monotonic() < deadline, "devd routing never restored"
                assert all(v.verify_batch(items))
                if br.state == br.CLOSED and v.stats()["tpu_sigs"] > before:
                    break
                time.sleep(0.02)
            recoveries.append(time.monotonic() - t0)

        recovery = statistics.median(recoveries)
        rows.append({
            "mode": "recovery", "platform": "sim",
            "kill_restart_cycles": N_KILLS,
            "recovery_s_median": round(recovery, 3),
            "recovery_s_all": [round(r, 3) for r in recoveries],
            "faults": plan.stats(),
            "breaker": br.stats(),
        })
        assert recovery <= MAX_RECOVERY_S, (
            f"recovery {recovery:.2f}s exceeds the {MAX_RECOVERY_S}s floor"
        )
        # round 11: the kill schedule is SCRAPE-visible — assert on the
        # telemetry registry's exported counters (the surface GET
        # /metrics serves), not by reaching into the harness objects
        from tendermint_tpu.libs import telemetry

        scraped = {
            f.name: f.samples[0][2]
            for f in telemetry.default_registry().collect() if f.samples
        }
        assert scraped.get("faults_kill", 0) >= N_KILLS, scraped
        assert scraped.get("faults_supervisor_kills", 0) >= N_KILLS
        assert scraped.get("faults_supervisor_restarts", 0) >= N_KILLS
    finally:
        sup.stop()
        gateway.reset_devd_breaker()
        try:
            os.unlink(sock)
        except OSError:
            pass

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": "devd chaos: recovery time + degraded-mode throughput",
        "max_recovery_s_asserted": MAX_RECOVERY_S,
        "rows": rows,
        "note": (
            "sim daemon holds device time constant; degraded row is the "
            "breaker-open CPU fallback; recovery is daemon-serving -> "
            "breaker-closed-and-devd-routed (fast probe windows: "
            "TENDERMINT_TPU_BREAKER_BACKOFF_S=0.1/cap 1.0)"
        ),
    }
    if not SMOKE:
        # bench_partset's convention: the tier-1 smoke gate asserts but
        # never writes — otherwise every `make tier1` would clobber the
        # recorded full-run artifact with reduced smoke numbers
        with open(os.path.join(ROOT, "BENCH_r08.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "devd_chaos_recovery_s",
        "value": rows[-1]["recovery_s_median"],
        "unit": "s",
        "degraded_delta": rows[1]["delta_vs_healthy"],
        "healthy_sigs_per_sec": rows[0]["sigs_per_sec"],
        "platform": "sim",
        "smoke": SMOKE,
    }))


if __name__ == "__main__":
    main()
