"""WAN + adversarial-load bench (round 18): consensus throughput and
commit skew of a real-TCP testnet under named WAN profiles, plus the
flood-shed liveness row (docs/netchaos.md).

BENCH_r12 measured the net under socket faults; this one measures it
under internet SHAPE (seeded latency/jitter/loss/bandwidth sampled from
`ops/netfaults.WAN_PROFILES`) and under protocol-fluent ATTACK (the
hostile-peer mempool flooder from tests/netchaos_common.py). Cross-node
timing comes from the round-15 `ops/fleet` plane — heights/s from the
stores, commit skew / quorum-formation from scraped traces only.

Rows:
- wan:<profile>:  heights/s + committed-tx/s + commit skew (median/max
                  over the fleet-timeline rows) per WAN profile; >= 3
                  profiles on a full run, one on the smoke
- flood_shed:     heights/s while a hostile peer floods garbage
                  signatures at the sig gate, vs the lan baseline —
                  liveness asserted >= MIN_FLOOD_RATIO x baseline and
                  the shed asserted visible in
                  p2p_adversary_flood_txs_rejected
- convergence:    final per-height byte-identity across every node

Asserted floors (chip-free — this gates `make wan-smoke` in tier1):
- every profiled window still commits (heights/s > 0)
- flood-window heights/s >= MIN_FLOOD_RATIO (default 1/3) x baseline
- >= 80% of the garbage flood visibly shed in telemetry
- final byte-identical convergence

BENCH_WAN_SMOKE=1 shrinks to 4 nodes / 1 profile / shorter windows for
the tier-1 gate (~40 s). Prints ONE JSON line like the other benches;
writes BENCH_r18.json on full runs.
Run from the repo root: python benches/bench_wan.py
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

SMOKE = os.environ.get("BENCH_WAN_SMOKE", "") == "1"
N_NODES = int(os.environ.get("BENCH_WAN_NODES", "4"))
WINDOW_S = float(os.environ.get("BENCH_WAN_WINDOW_S", "8" if SMOKE else "20"))
PROFILES = (
    ["continental"]
    if SMOKE
    else ["lan", "continental", "intercontinental", "lossy-mobile"]
)
FLOOD_TXS = int(os.environ.get("BENCH_WAN_FLOOD_TXS", "1500" if SMOKE else "4000"))
MIN_FLOOD_RATIO = float(os.environ.get("BENCH_WAN_MIN_FLOOD_RATIO", "0.33"))


def _committed_txs(net, upto: int) -> int:
    store = net.nodes[0].block_store
    return sum(
        store.load_block(h).header.num_txs for h in range(1, upto + 1)
    )


def _skew_row(urls, last: int = 12) -> dict:
    """Commit skew + quorum time from scrapes only (ops/fleet)."""
    from tendermint_tpu.ops import fleet

    snapshot = fleet.collect(urls, last=last)
    rows = fleet.build_timeline(
        {u: e.get("traces", []) for u, e in snapshot.items()}, last=last
    )
    skews = [
        r["commit_skew_s"] for r in rows
        if r.get("commit_skew_s") is not None and r["nodes_reporting"] >= 2
    ]
    quorums = [
        r["precommit_quorum_s_max"] for r in rows
        if r.get("precommit_quorum_s_max") is not None
    ]
    return {
        "timeline_rows": len(rows),
        "commit_skew_s_median": round(statistics.median(skews), 4) if skews else None,
        "commit_skew_s_max": round(max(skews), 4) if skews else None,
        "precommit_quorum_s_max": round(max(quorums), 4) if quorums else None,
    }


def main() -> None:
    # hermetic like tests/conftest.py: never dial a production daemon,
    # and pin the CPU platform before jax loads
    os.environ.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
    os.environ.setdefault("TENDERMINT_TPU_PLATFORM", "cpu")

    from netchaos_common import ChaosNet, MempoolFlooder, wait_until
    from tendermint_tpu.abci.apps.signedkv import make_sig_tx
    from tendermint_tpu.ops import fleet, netfaults

    root = tempfile.mkdtemp(prefix="bench-wan-")
    net = ChaosNet(N_NODES, root, app="signedkv")
    rows = []
    try:
        t0 = time.perf_counter()
        net.start()
        assert net.wait_height(2, timeout=150), net.heights()
        boot_s = time.perf_counter() - t0
        urls = [f"127.0.0.1:{n.rpc_port()}" for n in net.nodes]

        # light honest tx trickle keeps blocks non-trivial in every row
        seeds = [bytes([i + 1]) * 32 for i in range(4)]

        def pump(tag: str, n: int) -> None:
            for i in range(n):
                tx = make_sig_tx(seeds[i % 4], f"{tag}-{i}={i}".encode())
                net.broadcast_tx(tx, via=i % N_NODES)

        # -- per-profile windows ------------------------------------------
        lan_hps = None
        for profile in PROFILES:
            net.apply_wan(profile, seed=18)
            h0 = min(net.heights())
            tx0 = _committed_txs(net, h0)
            t0 = time.perf_counter()
            i = 0
            while time.perf_counter() - t0 < WINDOW_S:
                pump(f"{profile}-{i}", 2)
                i += 1
                time.sleep(0.5)
            assert net.wait_height(h0 + 1, timeout=90), (profile, net.heights())
            wall = time.perf_counter() - t0
            h1 = min(net.heights())
            hps = (h1 - h0) / wall
            assert hps > 0, f"no commits under profile {profile}"
            row = {
                "mode": f"wan:{profile}",
                "heights_per_s": round(hps, 3),
                "committed_tx_per_s": round(
                    (_committed_txs(net, h1) - tx0) / wall, 1
                ),
            }
            row.update(_skew_row(urls))
            wan = netfaults.telemetry_counters()
            row["wan_delays_applied"] = wan["netfaults_wan_delays_applied"]
            row["wan_loss_stalls"] = wan["netfaults_wan_loss_stalls"]
            rows.append(row)
            if profile == "lan":
                lan_hps = hps
        net.clear_wan()

        # -- flood-shed liveness row --------------------------------------
        # time-to-K-commits, baseline vs under-flood: a windowed
        # heights/s on a slow box quantizes to 0-2 commits and flakes
        # the ratio; the time to commit the SAME K heights compares
        # cleanly (the pump keeps running in both phases)
        K = 2

        def time_to_commits(tag: str, cap_s: float = 150.0) -> float:
            h0 = min(net.heights())
            t0 = time.perf_counter()
            i = 0
            while min(net.heights()) < h0 + K:
                assert time.perf_counter() - t0 < cap_s, (
                    tag, net.heights(), h0
                )
                pump(f"{tag}-{i}", 2)
                i += 1
                time.sleep(0.5)
            return time.perf_counter() - t0

        base_t = time_to_commits("base")

        url1 = urls[1]
        rejected0 = fleet.metric_value(
            fleet.fetch_metrics(url1),
            "p2p_adversary_flood_txs_rejected", default=0.0,
        )
        flooder = MempoolFlooder(
            "127.0.0.1", net.nodes[1].listener.internal_address().port,
            "netchaos",
        )
        try:
            sent = flooder.flood_garbage(FLOOD_TXS, seed=18)
            flood_t = time_to_commits("flood")
            assert wait_until(
                lambda: fleet.metric_value(
                    fleet.fetch_metrics(url1),
                    "p2p_adversary_flood_txs_rejected", default=0.0,
                ) - rejected0 >= 0.8 * sent,
                timeout=60,
            ), "flood not visibly shed"
        finally:
            flooder.close()
        shed = fleet.metric_value(
            fleet.fetch_metrics(url1),
            "p2p_adversary_flood_txs_rejected", default=0.0,
        ) - rejected0
        base_hps, flood_hps = K / base_t, K / flood_t
        # the liveness floor: consensus cadence flat within the stated
        # bound while the flood is shed
        assert flood_hps >= MIN_FLOOD_RATIO * base_hps, (
            f"flood degraded liveness: {K} heights took {flood_t:.1f}s "
            f"flooded vs {base_t:.1f}s baseline (floor {MIN_FLOOD_RATIO}x)"
        )
        rows.append({
            "mode": "flood_shed",
            "flood_txs_sent": sent,
            "flood_txs_shed": int(shed),
            "baseline_heights_per_s": round(base_hps, 3),
            "flood_heights_per_s": round(flood_hps, 3),
            "vs_baseline": round(flood_hps / base_hps, 2) if base_hps else None,
            "asserted_min_ratio": MIN_FLOOD_RATIO,
            "lan_heights_per_s": round(lan_hps, 3) if lan_hps else None,
        })

        # -- final byte-identity ------------------------------------------
        top = min(net.heights())
        net.assert_converged(top)
        rows.append({"mode": "convergence", "upto_height": top, "ok": True})
        boot_row = {"mode": "boot", "nodes": N_NODES, "boot_s": round(boot_s, 2)}
        rows.insert(0, boot_row)
    finally:
        net.stop()

    record = {
        "bench": "wan",
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": "cpu",
        "smoke": SMOKE,
        "rows": rows,
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r18.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
