"""BASELINE config 4: fast-sync replay — pipelined batch verify in
catch-up (blockchain/reactor.go:218-257).

Builds a chain of blocks each carrying a 1000-validator commit, then
replays it two ways through the exact code fast sync runs
(ValidatorSet.verify_commit / verify_commit_async + part-set rebuild):

- CPU: the reference-faithful loop — sequential per-signature verify,
  then part hashing, block by block;
- TPU: the production pipeline — block N's signature batch on the device
  while the host hashes block N+1's part set (verify_commit_async,
  exactly what BlockchainReactor._try_sync does).

Prints ONE JSON line. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.jitcache import enable as _enable_jit_cache
from tendermint_tpu.jitcache import platform_label

_enable_jit_cache()

N_VALS = int(os.environ.get("BENCH_N_VALS", "1000"))
N_BLOCKS = int(os.environ.get("BENCH_N_BLOCKS", "24"))
PART_SIZE = 64 * 1024
CHAIN_ID = "bench-fastsync"


def build_chain():
    """N_BLOCKS commits signed by N_VALS validators (signing is setup
    cost, excluded from measurement). Commits are built directly — the
    VoteSet ceremony would re-verify each signature during setup."""
    from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
    from tendermint_tpu.types import BlockID, Vote
    from tendermint_tpu.types.block_id import PartSetHeader
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.types.block import Commit
    from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT

    privs = [gen_priv_key_ed25519(f"fsync-{i}".encode()) for i in range(N_VALS)]
    vals = [Validator.new(p.pub_key(), 1) for p in privs]
    vs = ValidatorSet(vals)
    # sort privs into set order
    by_addr = {p.pub_key().address(): p for p in privs}
    privs = [by_addr[v.address] for v in vs.validators]

    commits = []
    for h in range(1, N_BLOCKS + 1):
        block_id = BlockID(bytes([h & 0xFF]) * 20, PartSetHeader(1, bytes([h & 0xFF]) * 20))
        precommits = []
        for i, p in enumerate(privs):
            v = Vote(
                validator_address=vs.validators[i].address,
                validator_index=i,
                height=h,
                round_=0,
                type_=VOTE_TYPE_PRECOMMIT,
                block_id=block_id,
            )
            precommits.append(v.with_signature(p.sign(v.sign_bytes(CHAIN_ID))))
        commits.append((block_id, Commit(block_id, precommits)))
    # synthetic 256KB block payloads to rebuild part sets from
    payloads = [bytes([h & 0xFF]) * (256 * 1024) for h in range(N_BLOCKS)]
    return vs, commits, payloads


def main() -> None:
    from tendermint_tpu.ops.gateway import Hasher, Verifier
    from tendermint_tpu.types.part_set import PartSet

    vs, commits, payloads = build_chain()
    verifier = Verifier(min_tpu_batch=32)
    hasher = Hasher()  # production policy: CPU hashing

    # group with the reactor's OWN rule so the bench measures exactly the
    # dispatch shapes _dispatch_speculative produces, and warm every
    # distinct group size (the tail group hits a smaller kernel bucket)
    from tendermint_tpu.blockchain.reactor import group_spans

    GROUP_TARGET = int(os.environ.get("BENCH_GROUP_SIG_TARGET", "4096"))
    spans = group_spans([N_VALS] * N_BLOCKS, GROUP_TARGET)
    for size in {j - i for i, j in spans}:
        warm = [(bid, i + 1, c) for i, (bid, c) in enumerate(commits[:size])]
        for fin in vs.verify_commits_async(CHAIN_ID, warm, verifier.verify_batch_async):
            fin()

    # -- CPU reference: sequential verify + hash, block by block ----------
    t0 = time.perf_counter()
    cpu_hash_s = 0.0
    for h, ((block_id, commit), payload) in enumerate(zip(commits, payloads), 1):
        vs.verify_commit(CHAIN_ID, block_id, h, commit)  # per-sig CPU loop
        th = time.perf_counter()
        PartSet.from_data(payload, PART_SIZE)
        cpu_hash_s += time.perf_counter() - th
    cpu_s = time.perf_counter() - t0

    # -- TPU pipeline: the reactor's speculative pipeline shape
    # (blockchain/reactor._dispatch_speculative): commits grouped into
    # device calls of ~GROUP_TARGET signatures, several calls in flight,
    # resolved while the host hashes part sets --------------------------
    DEPTH = int(os.environ.get("BENCH_PIPELINE_DEPTH", "8"))
    PASSES = int(os.environ.get("BENCH_PASSES", "2"))  # best-of: the chip
    # sits behind a shared tunnel, so single passes see contention noise
    tpu_s = float("inf")
    stages_best: dict = {}
    for _ in range(PASSES):
        t0 = time.perf_counter()
        stages = {"dispatch_s": 0.0, "part_hash_s": 0.0, "resolve_wait_s": 0.0}
        pending: list = []
        for g, g_end in spans:
            group = commits[g:g_end]
            ts = time.perf_counter()
            pending.extend(
                vs.verify_commits_async(
                    CHAIN_ID,
                    [(bid, g + i + 1, c) for i, (bid, c) in enumerate(group)],
                    verifier.verify_batch_async,
                )
            )
            stages["dispatch_s"] += time.perf_counter() - ts
            ts = time.perf_counter()
            for payload in payloads[g:g_end]:
                PartSet.from_data(payload, PART_SIZE, hasher=hasher.part_leaf_hashes)
            stages["part_hash_s"] += time.perf_counter() - ts
            ts = time.perf_counter()
            while len(pending) > DEPTH:
                pending.pop(0)()
            stages["resolve_wait_s"] += time.perf_counter() - ts
        ts = time.perf_counter()
        for fin in pending:
            fin()
        stages["resolve_wait_s"] += time.perf_counter() - ts
        elapsed = time.perf_counter() - t0
        if elapsed < tpu_s:
            tpu_s = elapsed
            stages_best = {k: round(v, 3) for k, v in stages.items()}
    # dispatch_s is host-serial work (structural checks + sign-bytes +
    # marshal); resolve_wait_s is time blocked on the device; part_hash_s
    # is host hashing. The residual bottleneck is whichever dominates —
    # recorded so the next optimization is measured, not guessed
    # (VERDICT r3 weak #6). NOTE: when the gateway is on its CPU fallback
    # (no accelerator), verification itself runs synchronously inside the
    # "dispatch" stage — only an accelerator run separates dispatch from
    # device wait.
    stages_best["other_s"] = round(tpu_s - sum(stages_best.values()), 3)

    total_sigs = N_VALS * N_BLOCKS
    print(
        json.dumps(
            {
                "metric": "fastsync_blocks_per_sec",
                "value": round(N_BLOCKS / tpu_s, 2),
                "unit": "blocks/s",
                "vs_baseline": round(cpu_s / tpu_s, 2),
                "detail": {
                    "validators": N_VALS,
                    "blocks": N_BLOCKS,
                    "cpu_blocks_per_sec": round(N_BLOCKS / cpu_s, 2),
                    "tpu_sigs_per_sec": round(total_sigs / tpu_s, 1),
                    "cpu_sigs_per_sec": round(total_sigs / cpu_s, 1),
                    "cpu_part_hash_s": round(cpu_hash_s, 3),
                    "pipeline_stages": stages_best,
                    "platform": platform_label(),
                    "gateway_stats": verifier.stats(),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
