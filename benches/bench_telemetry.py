"""Telemetry-plane bench + smoke (round 11): the instrumentation must
never silently tax the planes it watches, and the exposition surface
must actually serve scrapers.

Rows (written to BENCH_r11.json under "telemetry"; the rpc-load bench
owns the "rpc_scrape" section of the same file):

- observe_ns:   raw Histogram.observe cost (the hot-path primitive the
                devd/WAL/mempool instruments pay per event)
- gate_overhead: the mempool signed-burst gate (the `5_mempool` shape —
                SigBatcher -> gateway verify). ASSERTED < 2%: the bound
                is computed as (instrument events the burst actually
                executed) x (micro-measured worst-case per-event cost,
                with a 3x safety margin) / burst wall time — an UPPER
                bound on the instrumentation tax that stays meaningful
                on this 2-core box, where end-to-end A/B deltas swing
                +-20% run to run (the raw enabled-vs-disabled
                interleaved timings are recorded beside it as context,
                not asserted — measuring a real <0.1% delta through
                that noise would be a coin flip, and a guard that
                flakes is a guard that gets deleted). A regression that
                adds per-TX instrumentation (2048 events instead of 4)
                or a slow observe (lock convoy) moves the asserted
                bound by orders of magnitude and fails loudly.
- node_smoke:   boot a real kvstore node, scrape GET /metrics (valid
                0.0.4 text, >= 40 families spanning every plane), pull
                one consensus_trace and assert its segments sum to the
                height's wall clock within 5%

BENCH_TELEMETRY_SMOKE=1 shrinks the burst for the ~15 s tier-1 gate
(`make metrics-smoke`); the smoke asserts but never writes (the
bench_partset convention). Prints ONE JSON line. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_TELEMETRY_SMOKE", "") == "1"
N_SIGNED = int(os.environ.get(
    "BENCH_TELEMETRY_TXS", "2048" if SMOKE else "4096"
))
REPEATS = int(os.environ.get("BENCH_TELEMETRY_REPEATS",
                             "4" if SMOKE else "5"))
MAX_OVERHEAD_PCT = float(os.environ.get(
    "BENCH_TELEMETRY_MAX_OVERHEAD_PCT", "2.0"
))
MIN_FAMILIES = int(os.environ.get("BENCH_TELEMETRY_MIN_FAMILIES", "40"))


def bench_observe_ns() -> dict:
    """Raw instrument cost: one labeled + one bare observe."""
    from tendermint_tpu.libs import telemetry

    reg = telemetry.Registry()
    h = reg.histogram("bench_seconds")
    n = 200_000
    t0 = time.perf_counter()
    for i in range(n):
        h.observe(0.001)
    bare = (time.perf_counter() - t0) / n * 1e9
    hl = reg.histogram("bench_labeled_seconds", labelnames=("op",))
    child = hl.labels(op="verify")
    t0 = time.perf_counter()
    for i in range(n):
        child.observe(0.001)
    labeled = (time.perf_counter() - t0) / n * 1e9
    return {
        "observe_ns": round(bare, 1),
        "observe_labeled_child_ns": round(labeled, 1),
        "n": n,
    }


def _gate_burst_once(txs, want: int) -> tuple[float, int]:
    """One mempool signed-burst gate pass (the 5_mempool clean shape);
    returns (elapsed seconds, instrument observes executed)."""
    from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp, parse_sig_tx
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.config import test_config
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.mempool.mempool import SigBatcher
    from tendermint_tpu.ops.gateway import Verifier
    from tendermint_tpu.proxy.app_conn import AppConnMempool

    cfg = test_config().mempool
    cfg.root_dir = tempfile.mkdtemp(prefix="bench-telemetry-gate-")
    app = SignedKVStoreApp(verify_in_app=False)
    verifier = Verifier(min_tpu_batch=32)
    batcher = SigBatcher(verifier, parse_sig_tx, max_batch=512,
                         max_wait_s=0.002)
    mp = Mempool(cfg, AppConnMempool(LocalClient(app, threading.RLock())),
                 sig_batcher=batcher)
    # warm the verify path off the clock
    verifier.verify_batch([parse_sig_tx(t) for t in txs[:256]])
    observes0 = batcher._batch_hist.count
    t0 = time.perf_counter()
    for tx in txs:
        mp.check_tx(tx)
    deadline = time.perf_counter() + 120.0
    while mp.size() != want:
        assert time.perf_counter() < deadline, \
            f"gate drain stalled at {mp.size()}/{want}"
        mp.flush_app_conn()
        time.sleep(0.002)
    elapsed = time.perf_counter() - t0
    batcher.stop()
    return elapsed, batcher._batch_hist.count - observes0


def per_event_cost_ns(observe_row: dict) -> float:
    """The 3x-margined worst-case cost of one instrument event: the
    slower of the bare/labeled observe micro-measurements, tripled, +
    ~200ns for the perf_counter reads bracketing it. Shared by every
    computed-bound overhead guard (this gate + bench_fleet's p2p bound)
    so the two records never drift onto different cost models."""
    return 3.0 * max(observe_row["observe_ns"],
                     observe_row["observe_labeled_child_ns"]) + 200.0


def bench_gate_overhead(observe_row: dict) -> dict:
    """The histogram-overhead guard (module docstring has the method):
    asserted bound = events x 3x-margined per-event cost / wall time;
    the interleaved enabled/disabled end-to-end timings ride along as
    unasserted context."""
    from tendermint_tpu.abci.apps.signedkv import make_sig_tx
    from tendermint_tpu.libs import telemetry

    seeds = [bytes([i + 1]) * 32 for i in range(64)]
    txs = [
        make_sig_tx(seeds[i % 64], b"tk%06d=v%d" % (i, i))
        for i in range(N_SIGNED)
    ]
    on_s, off_s = float("inf"), float("inf")
    observes = 0
    for i in range(REPEATS):
        # alternate arm ORDER each repeat: box-load drift (this is a
        # 2-core box; anything else running lands on the bench) must
        # not systematically favor one arm's min
        order = (True, False) if i % 2 == 0 else (False, True)
        for on in order:
            telemetry.set_enabled(on)
            try:
                t, n_obs = _gate_burst_once(txs, N_SIGNED)
            finally:
                telemetry.set_enabled(True)
            if on:
                on_s = min(on_s, t)
                observes = max(observes, n_obs)
            else:
                off_s = min(off_s, t)
    assert observes >= 1, "instrumented burst recorded no observes"
    per_event_ns = per_event_cost_ns(observe_row)
    overhead_pct = observes * per_event_ns / (on_s * 1e9) * 100.0
    raw_delta_pct = (on_s - off_s) / off_s * 100.0
    row = {
        "shape": "5_mempool signed-burst gate (clean)",
        "signed_txs": N_SIGNED,
        "repeats_min_of": REPEATS,
        "instrument_events": observes,
        "per_event_cost_ns_3x_margin": round(per_event_ns, 1),
        "overhead_pct_bound": round(overhead_pct, 4),
        "max_overhead_pct_asserted": MAX_OVERHEAD_PCT,
        "enabled_s": round(on_s, 4),
        "disabled_s": round(off_s, 4),
        "enabled_sigs_per_sec": round(N_SIGNED / on_s, 1),
        "disabled_sigs_per_sec": round(N_SIGNED / off_s, 1),
        "raw_ab_delta_pct_unasserted": round(raw_delta_pct, 2),
    }
    assert overhead_pct < MAX_OVERHEAD_PCT, (
        f"hot-path instrumentation bound {overhead_pct:.3f}% "
        f"(floor {MAX_OVERHEAD_PCT}%) on the mempool gate: {row}"
    )
    return row


def bench_node_smoke() -> dict:
    """Boot a node, scrape /metrics, pull a consensus_trace."""
    from tendermint_tpu.config import reset_test_root
    from tendermint_tpu.node import default_new_node
    from tendermint_tpu.rpc.client import HTTPClient

    home = tempfile.mkdtemp(prefix="bench-telemetry-node-")
    cfg = reset_test_root(home)
    cfg.base.proxy_app = "kvstore"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    node = default_new_node(cfg)
    node.start()
    try:
        deadline = time.time() + 60
        while node.block_store.height() < 2 and time.time() < deadline:
            time.sleep(0.1)
        assert node.block_store.height() >= 2, "node never committed"
        url = f"http://127.0.0.1:{node.rpc_port()}"

        t0 = time.perf_counter()
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
            ctype = r.headers["Content-Type"]
            text = r.read().decode()
        scrape_ms = (time.perf_counter() - t0) * 1000
        assert ctype.startswith("text/plain; version=0.0.4"), ctype
        families = {}
        for line in text.strip().splitlines():
            if line.startswith("# TYPE "):
                _h, _t, name, kind = line.split()
                families[name] = kind
        assert len(families) >= MIN_FAMILIES, (
            f"{len(families)} families < {MIN_FAMILIES}"
        )
        for fam in ("consensus_height", "wal_format", "gateway_breaker_state",
                    "gateway_verify_tpu_sigs", "gateway_hash_tpu_leaves",
                    "mempool_size", "statesync_snapshots", "fastsync_active",
                    "p2p_peers_outbound"):
            assert fam in families, f"missing family {fam}"
        assert families["wal_fsync_seconds"] == "histogram"

        client = HTTPClient(f"127.0.0.1:{node.rpc_port()}")
        traces = client.consensus_trace(last=3)["traces"]
        assert traces, "no consensus traces"
        t = traces[0]
        total = sum(t["segments"].values())
        tol = max(0.05 * t["wall_s"], 0.005)
        assert abs(total - t["wall_s"]) <= tol, (total, t["wall_s"])
        assert "verify_cpu_sigs" in t["device"]
        # flat RPC and scrape agree on the legacy gauge set
        flat = client.metrics()
        missing = [k for k in flat if k not in families]
        assert not missing, f"scrape lost flat gauges: {missing[:8]}"
        return {
            "families": len(families),
            "scrape_ms": round(scrape_ms, 2),
            "flat_keys": len(flat),
            "traced_heights": len(traces),
            "trace_wall_s": t["wall_s"],
            "trace_segments_sum_s": round(total, 6),
        }
    finally:
        node.stop()


def main() -> None:
    observe_row = bench_observe_ns()
    rows = {
        "observe": observe_row,
        "gate_overhead": bench_gate_overhead(observe_row),
        "node_smoke": bench_node_smoke(),
    }
    record_path = os.path.join(ROOT, "BENCH_r11.json")
    if not SMOKE:
        # merge-write: bench_rpc_load owns the "rpc_scrape" section of
        # the same artifact (never clobber it)
        try:
            with open(record_path) as f:
                record = json.load(f)
        except (OSError, ValueError):
            record = {}
        record["recorded_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        record["metric"] = (
            "telemetry plane: instrumentation overhead + exposition smoke"
        )
        record["telemetry"] = rows
        with open(record_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "telemetry_gate_overhead_pct",
        "value": rows["gate_overhead"]["overhead_pct_bound"],
        "unit": "%",
        "vs_baseline": 1.0,  # host-path guard: no reference numbers exist
        "detail": {
            "families": rows["node_smoke"]["families"],
            "scrape_ms": rows["node_smoke"]["scrape_ms"],
            "observe_ns": rows["observe"]["observe_ns"],
            "smoke": SMOKE,
        },
    }))


if __name__ == "__main__":
    main()
