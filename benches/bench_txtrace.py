"""Tx-lifecycle tracing bench + smoke (round 17): the request-level
observability plane must attribute a tx's latency correctly and must
never tax the ingress path it watches.

Rows (written to BENCH_r17.json):

- stamp_costs:   per-event costs of the EXACT hot-path sequences — the
                 inline countdown every untraced check_tx pays, the
                 batch-granular gate stamp, the sampled-tx ingress slow
                 path, a stamp probe with traces in flight
- gate_overhead: the mempool signed-burst gate (the `5_mempool` shape)
                 with a TxTraceRecorder wired at DEFAULT sampling.
                 ASSERTED < 2% as a computed bound (the
                 benches/bench_telemetry.py discipline: end-to-end A/B
                 deltas on this 2-core box swing more than the real
                 cost, so a bound is what's asserted and the raw A/B
                 delta rides along unasserted): sum over event CLASSES
                 of (events the burst executed) x (that class's
                 measured MARGINAL cost — the exact production
                 sequence, with the empty-loop baseline subtracted
                 from the loop-dominated micro measurements) x 1.5
                 margin / burst wall. The margin is 1.5x where
                 bench_telemetry used 3x+200ns because these are not
                 proxy costs: each class is measured as the exact
                 sequence at the exact workload shape (batch size,
                 active-table size), whereas the telemetry bench
                 margined a best-case bare observe standing in for
                 varied call sites — and the raw interleaved A/B delta
                 recorded beside the bound shows the true tax sits in
                 this box's measurement noise (<2% swing run to run). A regression that
                 re-introduces per-tx method calls on the gate path
                 (the round-11 docstring's exact warning) moves this
                 bound by an order of magnitude and fails loudly.
- attribution:   a live single-validator node committing a sampled
                 signed workload: per-stage p50/p99 spans across the
                 traced txs, with EVERY completed trace's spans-through-
                 block_commit ASSERTED to sum within 10% of its
                 measured end-to-end commit latency (the acceptance
                 bar; the spans telescope, so this guards the stamping
                 sites end to end)
- wedge_dump:    the flight recorder's dump path on the same live node:
                 ring size, dump latency, artifact bytes, and the dump
                 parsing back as JSON with monotonic timestamps

BENCH_TXTRACE_SMOKE=1 shrinks the workload for the tier-1 gate
(`make txtrace-smoke`); the smoke asserts but never writes (the
bench_partset convention). Prints ONE JSON line. Run from the repo
root.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_TXTRACE_SMOKE", "") == "1"
N_SIGNED = int(os.environ.get("BENCH_TXTRACE_TXS",
                              "2048" if SMOKE else "4096"))
N_NODE_TXS = int(os.environ.get("BENCH_TXTRACE_NODE_TXS",
                                "24" if SMOKE else "80"))
MAX_OVERHEAD_PCT = float(os.environ.get(
    "BENCH_TXTRACE_MAX_OVERHEAD_PCT", "2.0"
))
SPAN_SUM_TOL = 0.10  # the acceptance criterion


def bench_stamp_costs() -> dict:
    """Per-event costs of the EXACT hot-path sequences (min of 3 runs
    each; tight-loop, loop overhead deliberately left in — the
    measurements overstate the marginal cost)."""
    from tendermint_tpu.abci.apps.signedkv import make_sig_tx
    from tendermint_tpu.libs.txtrace import TxTraceRecorder

    def min_of(fn, runs=3):
        return min(fn() for _ in range(runs))

    n = 200_000

    # empty-loop baseline: the for/range machinery is NOT part of the
    # production sequences (check_tx's surrounding code exists either
    # way), so loop-dominated measurements subtract it — the bound
    # prices the MARGINAL cost of the added instructions
    def loop_baseline():
        t0 = time.perf_counter()
        for _ in range(n):
            pass
        return (time.perf_counter() - t0) / n * 1e9

    base_ns = min_of(loop_baseline)

    # 1. the inline countdown every untraced check_tx pays — the exact
    # mempool.check_tx sequence against a bound-tick holder
    class _Holder:
        pass

    holder = _Holder()
    rec = TxTraceRecorder(first_k=0, sample_n=0)
    rec.bind_tick(holder)

    def tick_cost():
        t0 = time.perf_counter()
        for _ in range(n):
            holder._trace_tick -= 1
            if holder._trace_tick <= 0:
                pass  # never fires with sampling disarmed
        return (time.perf_counter() - t0) / n * 1e9

    tick_ns = max(1.0, min_of(tick_cost) - base_ns)

    # 2. the batch-granular gate stamp: one stamp_gate_batch call over
    # a realistic 512-entry verdict batch with traces in flight
    seeds = [bytes([i + 1]) * 32 for i in range(8)]
    batch_txs = [
        make_sig_tx(seeds[i % 8], b"gc%05d=v" % i) for i in range(512)
    ]
    for t in batch_txs:
        hash(t)  # the mempool cache hashes every tx before the gate
    rec2 = TxTraceRecorder(first_k=64, sample_n=0, max_active=64)
    for t in batch_txs[:32]:
        rec2.maybe_trace(t)
    entries = [(t, None) for t in batch_txs]

    def gate_cost():
        m = 200
        t0 = time.perf_counter()
        for _ in range(m):
            rec2.stamp_gate_batch(entries, at=1.0)
        return (time.perf_counter() - t0) / m * 1e9

    gate_batch_ns = min_of(gate_cost)

    # 3. the sampled-tx ingress slow path (lock + table insert; the tx
    # hash is deferred to seal time by design). Production tables cap
    # at max_active (256 default) — measure at that shape, not against
    # a pathological ever-growing dict
    def ingress_cost():
        m = 250
        total = 0.0
        for r_i in range(8):
            r = TxTraceRecorder(first_k=1 << 30, sample_n=0,
                                max_active=1 << 30)
            txs = [b"ing%02d%06d=v" % (r_i, i) for i in range(m)]
            t0 = time.perf_counter()
            for t in txs:
                r.ingress(t)
            total += time.perf_counter() - t0
        return total / (8 * m) * 1e9

    ingress_ns = min_of(ingress_cost)

    # 4. a per-tx stamp probe with traces in flight (the block-
    # granularity sites: stamp_present over a committed block)
    rec3 = TxTraceRecorder(first_k=4, sample_n=0)
    rec3.maybe_trace(batch_txs[0])
    probe = batch_txs[1]

    def stamp_cost():
        t0 = time.perf_counter()
        for _ in range(n):
            rec3.stamp(probe, "proposal")
        return (time.perf_counter() - t0) / n * 1e9

    stamp_ns = max(1.0, min_of(stamp_cost) - base_ns)
    return {
        "loop_baseline_ns": round(base_ns, 1),
        "inline_tick_ns": round(tick_ns, 1),
        "gate_batch_stamp_ns": round(gate_batch_ns, 1),
        "ingress_slow_path_ns": round(ingress_ns, 1),
        "stamp_probe_ns": round(stamp_ns, 1),
        "n": n,
    }


def _gate_burst_once(txs, want: int, recorder) -> tuple[float, int]:
    """One mempool signed-burst pass (the 5_mempool clean shape) with
    `recorder` wired; returns (elapsed, tracing events executed)."""
    from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp, parse_sig_tx
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.config import test_config
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.mempool.mempool import SigBatcher
    from tendermint_tpu.ops.gateway import Verifier
    from tendermint_tpu.proxy.app_conn import AppConnMempool

    cfg = test_config().mempool
    cfg.root_dir = tempfile.mkdtemp(prefix="bench-txtrace-gate-")
    app = SignedKVStoreApp(verify_in_app=False)
    verifier = Verifier(min_tpu_batch=32)
    batcher = SigBatcher(verifier, parse_sig_tx, max_batch=512,
                         max_wait_s=0.002)
    mp = Mempool(cfg, AppConnMempool(LocalClient(app, threading.RLock())),
                 sig_batcher=batcher)
    if recorder is not None:
        mp.txtrace = recorder
    verifier.verify_batch([parse_sig_tx(t) for t in txs[:256]])
    batches0 = recorder.gate_batches if recorder is not None else 0
    t0 = time.perf_counter()
    for tx in txs:
        mp.check_tx(tx)
    deadline = time.perf_counter() + 120.0
    while mp.size() != want:
        assert time.perf_counter() < deadline, \
            f"gate drain stalled at {mp.size()}/{want}"
        mp.flush_app_conn()
        time.sleep(0.002)
    elapsed = time.perf_counter() - t0
    batcher.stop()
    if recorder is None:
        return elapsed, {}
    # event classes the burst executed (each bounded separately)
    events = {
        "ticks": want,  # one inline countdown per check_tx
        "gate_batches": recorder.gate_batches - batches0,
        "ingress": recorder.sampled,
        "stamps": 0,  # no consensus in this shape: no block stamps
    }
    return elapsed, events


MARGIN = 1.5  # on exact-sequence measurements (module docstring)


def bench_gate_overhead(stamp_row: dict) -> dict:
    """Computed-bound tracing tax on the signed-burst shape, asserted
    under the established 2% instrumentation floor (per-class bound,
    module docstring has the margin rationale)."""
    from tendermint_tpu.abci.apps.signedkv import make_sig_tx
    from tendermint_tpu.libs.txtrace import TxTraceRecorder

    seeds = [bytes([i + 1]) * 32 for i in range(64)]
    txs = [
        make_sig_tx(seeds[i % 64], b"tt%06d=v%d" % (i, i))
        for i in range(N_SIGNED)
    ]
    on_s, off_s = float("inf"), float("inf")
    events: dict = {}
    repeats = 3 if SMOKE else 4
    for i in range(repeats):
        order = (True, False) if i % 2 == 0 else (False, True)
        for traced in order:
            rec = TxTraceRecorder() if traced else None  # default knobs
            t, ev = _gate_burst_once(txs, N_SIGNED, rec)
            if traced:
                on_s = min(on_s, t)
                for k, v in ev.items():
                    events[k] = max(events.get(k, 0), v)
            else:
                off_s = min(off_s, t)
    cost_ns = {
        "ticks": stamp_row["inline_tick_ns"],
        "gate_batches": stamp_row["gate_batch_stamp_ns"],
        "ingress": stamp_row["ingress_slow_path_ns"],
        "stamps": stamp_row["stamp_probe_ns"],
    }
    bound_ns = sum(events[k] * cost_ns[k] * MARGIN for k in events)
    overhead_pct = bound_ns / (on_s * 1e9) * 100.0
    row = {
        "shape": "5_mempool signed-burst gate + default-sampled txtrace",
        "signed_txs": N_SIGNED,
        "event_classes": events,
        "per_class_cost_ns": cost_ns,
        "margin": MARGIN,
        "overhead_pct_bound": round(overhead_pct, 4),
        "max_overhead_pct_asserted": MAX_OVERHEAD_PCT,
        "traced_s": round(on_s, 4),
        "untraced_s": round(off_s, 4),
        "raw_ab_delta_pct_unasserted": round(
            (on_s - off_s) / off_s * 100.0, 2
        ),
    }
    assert overhead_pct < MAX_OVERHEAD_PCT, (
        f"tx-lifecycle tracing bound {overhead_pct:.3f}% "
        f"(floor {MAX_OVERHEAD_PCT}%) on the signed-burst gate: {row}"
    )
    return row


def _pctl(vals: list, q: float) -> float | None:
    if not vals:
        return None
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def bench_node_attribution() -> tuple[dict, dict]:
    """Live-node rows: per-stage attribution on a loaded chain + the
    wedge-dump artifact."""
    from tendermint_tpu.config import reset_test_root
    from tendermint_tpu.libs.txtrace import STAGES
    from tendermint_tpu.node import default_new_node
    from tendermint_tpu.rpc.client import HTTPClient

    # sample aggressively: the bench wants many traced txs
    os.environ["TENDERMINT_TXTRACE_FIRST_K"] = "4"
    os.environ["TENDERMINT_TXTRACE_SAMPLE_N"] = "4"
    home = tempfile.mkdtemp(prefix="bench-txtrace-node-")
    cfg = reset_test_root(home)
    cfg.base.proxy_app = "kvstore"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    node = default_new_node(cfg)
    node.start()
    try:
        deadline = time.time() + 60
        while node.block_store.height() < 1 and time.time() < deadline:
            time.sleep(0.1)
        client = HTTPClient(f"127.0.0.1:{node.rpc_port()}")
        t0 = time.perf_counter()
        for i in range(N_NODE_TXS):
            client.broadcast_tx_async(tx=(b"bt%05d=v%d" % (i, i)).hex())
        # drain: every submitted tx committed
        deadline = time.time() + 120
        while node.mempool.size() > 0 and time.time() < deadline:
            time.sleep(0.05)
        assert node.mempool.size() == 0, "workload never drained"
        load_s = time.perf_counter() - t0
        time.sleep(0.5)  # let the tail height's event flush seal traces

        traces = client.tx_trace(last=500)["traces"]
        done = [t for t in traces if t["outcome"] == "committed"]
        assert done, "no sampled tx completed on the loaded chain"
        # THE acceptance assert: every completed trace's spans through
        # block_commit sum within 10% of its commit latency
        commit_idx = STAGES.index("block_commit")
        worst_err = 0.0
        for t in done:
            span_sum = sum(
                v for k, v in t["spans"].items()
                if STAGES.index(k) <= commit_idx
            )
            lat = t["commit_latency_s"]
            err = abs(span_sum - lat) / max(lat, 1e-9)
            worst_err = max(worst_err, err)
            assert err <= SPAN_SUM_TOL or abs(span_sum - lat) < 1e-4, (
                f"span sum {span_sum} vs commit latency {lat} "
                f"({err * 100:.1f}% off): {t}"
            )
        per_stage = {}
        for stage in STAGES:
            vals = [t["spans"][stage] for t in done if stage in t["spans"]]
            if vals:
                per_stage[stage] = {
                    "p50_ms": round(_pctl(vals, 0.50) * 1e3, 3),
                    "p99_ms": round(_pctl(vals, 0.99) * 1e3, 3),
                    "n": len(vals),
                }
        attribution = {
            "workload_txs": N_NODE_TXS,
            "workload_s": round(load_s, 3),
            "sampled_completed": len(done),
            "commit_latency_p50_ms": round(
                _pctl([t["commit_latency_s"] for t in done], 0.5) * 1e3, 2
            ),
            "commit_latency_p99_ms": round(
                _pctl([t["commit_latency_s"] for t in done], 0.99) * 1e3, 2
            ),
            "visible_latency_p50_ms": round(
                _pctl([t["visible_latency_s"] for t in done], 0.5) * 1e3, 2
            ),
            "span_sum_worst_err_pct": round(worst_err * 100, 3),
            "span_sum_tol_pct_asserted": SPAN_SUM_TOL * 100,
            "per_stage": per_stage,
        }

        # -- wedge-dump row: the black-box artifact off the same node --
        rec = node.flightrec
        t0 = time.perf_counter()
        path = rec.dump("bench_wedge")
        dump_ms = (time.perf_counter() - t0) * 1e3
        assert path is not None and os.path.exists(path)
        with open(path) as f:
            payload = json.load(f)
        ts = [e["t"] for e in payload["events"]]
        assert ts == sorted(ts), "dump timestamps not monotonic"
        assert payload["counters"].get("height", 0) >= 1
        wedge = {
            "ring_events": len(payload["events"]),
            "recorded_total": payload["recorded_total"],
            "dump_ms": round(dump_ms, 2),
            "dump_bytes": os.path.getsize(path),
            "counters_keys": sorted(payload["counters"]),
        }
        return attribution, wedge
    finally:
        node.stop()
        os.environ.pop("TENDERMINT_TXTRACE_FIRST_K", None)
        os.environ.pop("TENDERMINT_TXTRACE_SAMPLE_N", None)


def main() -> None:
    stamp_row = bench_stamp_costs()
    gate_row = bench_gate_overhead(stamp_row)
    attribution, wedge = bench_node_attribution()
    rows = {
        "stamp_costs": stamp_row,
        "gate_overhead": gate_row,
        "attribution": attribution,
        "wedge_dump": wedge,
    }
    if not SMOKE:
        record = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "metric": "tx-lifecycle tracing: per-stage attribution + "
                      "overhead bound + flight-recorder dump",
            **rows,
        }
        with open(os.path.join(ROOT, "BENCH_r17.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "txtrace_overhead_pct",
        "value": rows["gate_overhead"]["overhead_pct_bound"],
        "unit": "%",
        "vs_baseline": 1.0,  # host-path guard: no reference numbers exist
        "detail": {
            "commit_latency_p50_ms": attribution["commit_latency_p50_ms"],
            "span_sum_worst_err_pct": attribution["span_sum_worst_err_pct"],
            "sampled_completed": attribution["sampled_completed"],
            "wedge_dump_bytes": wedge["dump_bytes"],
            "smoke": SMOKE,
        },
    }))


if __name__ == "__main__":
    main()
