"""BENCH_r19: bounded-retention lifecycle (docs/state-sync.md § Retention).

Rows (all chip-free):

- disk-per-height (ALWAYS, asserted): two live single-validator
  sqlite-backed nodes commit the SAME tx-carrying workload — one with
  [pruning] armed (+ statesync producer live), one archive — and the
  steady-state disk growth per height is compared AFTER the pruning
  horizon engages. The pruned node's bytes/height must undercut the
  archive node's (floor BENCH_RETENTION_MAX_RATIO, default 0.8): disk
  bounded by retention, not chain length. This ~200-height pass is the
  tier-1 retention smoke the ISSUE names (`make retention-smoke`).
- offerer-ban-latency (ALWAYS, asserted): a joining node restores from
  the pruned node while a FORGED-manifest offerer, a CORRUPT-chunk
  offerer, and a STALLING offerer attack the statesync channel; the
  row records seconds from attack start to each kind's scrape-visible
  ban, asserts all three land inside the budget, and asserts the
  restore still completes from the honest source.

BENCH_RETENTION_SMOKE=1 shrinks sizes for the tier-1 gate; the smoke
asserts but never writes BENCH_r19.json (bench_partset's convention).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_RETENTION_SMOKE", "") == "1"
N_HEIGHTS = int(os.environ.get(
    "BENCH_RETENTION_HEIGHTS", "240" if SMOKE else "400"
))
RETAIN = int(os.environ.get("BENCH_RETENTION_RETAIN", "30"))
PRUNE_INTERVAL = 10
SNAPSHOT_INTERVAL = 20
# keep exactly 2 snapshots: the deepest retention floor is then the
# ~40-height snapshot window, so the pruned node reaches its disk
# equilibrium well before the measurement window opens at N/2 (a wider
# window measured half pre-equilibrium growth and flaked the ratio)
SNAPSHOT_KEEP = 2
MAX_RATIO = float(os.environ.get("BENCH_RETENTION_MAX_RATIO", "0.8"))
BAN_BUDGET_S = float(os.environ.get("BENCH_RETENTION_BAN_BUDGET_S", "90"))

# retention knobs the nodes read at boot: a small tree-version window
# (kvstore's default 64 would pin the floor far above the operator
# target at bench scale), WAL chunks small enough to rotate (and so to
# prune) inside the run, and fast statesync windows for the ban row
os.environ.setdefault("TENDERMINT_STATETREE_KEEP_VERSIONS", "24")
os.environ.setdefault("TENDERMINT_WAL_CHUNK_BYTES", "65536")
os.environ.setdefault("TENDERMINT_STATESYNC_WINDOW", "4")
os.environ.setdefault("TENDERMINT_STATESYNC_CHUNK_TIMEOUT_S", "2")
os.environ.setdefault("TENDERMINT_STATESYNC_STALL_BAN", "2")
os.environ.setdefault("TENDERMINT_STATESYNC_DISCOVERY_S", "3")

from tests.netchaos_common import (  # noqa: E402
    CHAIN_ID,
    ChaosNet,
    hostile_offerer_matrix,
    wait_until,
)


_TX_SEQ = iter(range(1 << 30))  # unique across _drive calls (dedup cache)


def _drive(net: "ChaosNet", target: int, label: str) -> None:
    """Commit to `target` heights with a light tx per height so blocks
    carry real bytes (empty blocks would flatter the archive node)."""
    while net.nodes[0].block_store.height() < target:
        net.broadcast_tx(
            b"%s-%d=%s" % (label.encode(), next(_TX_SEQ), b"v" * 200), via=0
        )
        h = net.nodes[0].block_store.height()
        assert wait_until(
            lambda: net.nodes[0].block_store.height() > h, timeout=60
        ), f"{label}: stalled at height {h}"


def bench_disk_per_height(root: str) -> tuple[dict, "ChaosNet"]:
    """Steady-state disk growth per height, pruned vs archive. Returns
    the row AND the pruned net still running (the ban row reuses it)."""
    nets = {}
    rates = {}
    disks = {}
    for label, retain in (("pruned", RETAIN), ("archive", 0)):
        net = ChaosNet(
            1, os.path.join(root, label), db_backend="sqlite",
            snapshot_interval=SNAPSHOT_INTERVAL, snapshot_full_every=1,
            snapshot_chunk_size=4096, snapshot_keep=SNAPSHOT_KEEP,
            # tx-driven cadence: blocks commit per submitted tx, idle
            # heights tick slowly — snapshot lifetime then covers the
            # ban row's restore (see bench_offerer_ban_latency)
            height_throttle_s=0.25,
            retain_blocks=retain, prune_interval=PRUNE_INTERVAL,
        )
        net.start()
        # warm up past the point where the pruned node's horizon engages
        # (operator target + tree keep + snapshot window all satisfied)
        # AND the sqlite file reaches its free-page equilibrium, then
        # measure the steady-state stretch
        warmup = max(2 * RETAIN, N_HEIGHTS // 2)
        _drive(net, warmup, label)
        h1, d1 = net.nodes[0].block_store.height(), net.disk_bytes()
        _drive(net, N_HEIGHTS, label)
        h2, d2 = net.nodes[0].block_store.height(), net.disk_bytes()
        rates[label] = (d2 - d1) / max(1, h2 - h1)
        disks[label] = d2
        if label == "pruned":
            m = net.nodes[0].telemetry.flatten()
            assert m["blockstore_pruned_heights_total"] > 0, (
                "pruning never engaged at bench scale"
            )
            assert net.nodes[0].block_store.base() > 1
            nets[label] = net  # kept running for the ban row
        else:
            net.stop()
    ratio = rates["pruned"] / max(rates["archive"], 1.0)
    row = {
        "name": "disk_per_height",
        "heights": N_HEIGHTS,
        "retain_blocks": RETAIN,
        "pruned_bytes_per_height": round(rates["pruned"]),
        "archive_bytes_per_height": round(rates["archive"]),
        "pruned_final_disk_bytes": disks["pruned"],
        "archive_final_disk_bytes": disks["archive"],
        "ratio": round(ratio, 3),
        "max_ratio_asserted": MAX_RATIO,
        "pruned_store_base": nets["pruned"].nodes[0].block_store.base(),
        "wal_chunks_pruned": nets["pruned"].nodes[0].telemetry.flatten()[
            "pruning_wal_chunks_pruned"
        ],
    }
    return row, nets["pruned"]


def bench_offerer_ban_latency(net: "ChaosNet") -> dict:
    """Seconds from attack start to each offerer kind's ban on a live
    restoring node. The source chain is throttled first so its producer
    cannot race a NEWER honest snapshot past the pinned attack heights
    mid-restore (the picker always takes the max offered height)."""
    src = net.nodes[0]
    ccfg = src.config.consensus
    ccfg.timeout_commit = 1.0
    ccfg.skip_timeout_commit = False
    ccfg.create_empty_blocks_interval = 2.0  # idle heights every ~2-3 s
    time.sleep(1.0)

    h_s = max(src.snapshot_store.heights())
    honest = src.snapshot_store.load_manifest(h_s)
    chunks = [
        src.snapshot_store.load_chunk(h_s, i) for i in range(honest.chunks)
    ]
    # the forged offer at h_s+1 needs header h_s+2 on chain for its
    # light walk to SUCCEED (the binding check, not a transport miss,
    # must be what proves the lie); idle heights tick every ~2-3 s
    assert wait_until(
        lambda: src.block_store.height() >= h_s + 2, timeout=60
    ), (src.block_store.height(), h_s)

    joiner = net.start_node(1, pv=None, statesync_from=[0])
    jport = joiner.listener.internal_address().port
    t0 = time.monotonic()
    offerers = hostile_offerer_matrix(
        "127.0.0.1", jport, CHAIN_ID, honest, chunks
    )
    reactor = joiner.statesync_reactor
    latencies = {}
    try:
        deadline = time.monotonic() + BAN_BUDGET_S
        while time.monotonic() < deadline and len(latencies) < 3:
            for kind in ("forged", "corrupt", "stall"):
                if kind not in latencies and getattr(
                    reactor, f"offerer_bans_{kind}"
                ) > 0:
                    latencies[kind] = round(time.monotonic() - t0, 2)
            time.sleep(0.05)
        assert len(latencies) == 3, (
            f"not every offerer kind banned within {BAN_BUDGET_S}s: "
            f"{latencies}; reactor={reactor.stats()}"
        )
        assert wait_until(
            lambda: joiner.block_store.base() > 1, timeout=120
        ), "restore did not complete from the honest source"
        assert joiner.block_store.base() == h_s
    finally:
        for o in offerers.values():
            o.close()
    return {
        "name": "offerer_ban_latency",
        "ban_latency_s": latencies,
        "ban_budget_s": BAN_BUDGET_S,
        "restored_base": joiner.block_store.base(),
        "restore_completed": True,
    }


def main() -> None:
    root = tempfile.mkdtemp(prefix="bench-retention-")
    pruned_net = None
    try:
        disk_row, pruned_net = bench_disk_per_height(root)
        ban_row = bench_offerer_ban_latency(pruned_net)
    finally:
        if pruned_net is not None:
            pruned_net.stop()
        shutil.rmtree(root, ignore_errors=True)

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": (
            "retention: disk bytes/height pruned vs archive + "
            "adversarial statesync offerer ban latency"
        ),
        "smoke": SMOKE,
        "rows": [disk_row, ban_row],
        "note": (
            "both rows chip-free; disk rates measured over the "
            "steady-state stretch after the pruning horizon engages"
        ),
    }
    # assert BEFORE writing (a failed run must not replace the artifact)
    assert disk_row["ratio"] < MAX_RATIO, (
        f"pruned node grows {disk_row['ratio']}x the archive rate "
        f"(>{MAX_RATIO}): retention is not bounding disk"
    )
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r19.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "retention_disk_bytes_per_height",
        "value": disk_row["pruned_bytes_per_height"],
        "unit": "B/height",
        "archive_bytes_per_height": disk_row["archive_bytes_per_height"],
        "ratio": disk_row["ratio"],
        "ban_latency_s": ban_row["ban_latency_s"],
        "platform": "cpu",
        "smoke": SMOKE,
    }))


if __name__ == "__main__":
    main()
