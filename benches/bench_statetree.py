"""BENCH_r13: authenticated state tree + delta snapshots
(docs/state-tree.md).

Rows (all chip-free except the auto-appended live-daemon row):

- commit-update vs full-rebuild (ALWAYS, asserted): an N-key tree takes
  an M-key update; incremental commit (O(changed * log n) dirty-node
  recompute) vs rebuilding the whole tree from its map — the reason the
  per-commit app hash no longer costs O(n log n).
- proof correctness (ALWAYS, asserted): membership + absence proofs
  verify against the committed root; a tampered value, a wrong-root
  proof, and a stripped membership each FAIL verification.
- snapshot full-vs-delta (ALWAYS, asserted): a devchain with a large
  seeded state and small per-interval churn produces a full snapshot
  and a delta; delta bytes must land meaningfully below full bytes
  (< BENCH_STATETREE_DELTA_MAX of full, default 0.5) at the larger
  state size, a delta-chain restore must end byte-identical to the
  full restore, and an injected corrupt chunk must be REJECTED — the
  correctness gate `make statetree-smoke` runs in tier 1.
- sim-node-hash (full bench only; digest PARITY asserted, the ratio
  recorded unasserted): the commit plane's bulk hash workload — REAL
  tree-node preimages digested against a sim-device daemon, streamed
  (`hash_stream`) vs single-shot (`hash_batch`). Node preimages are
  tiny (~40-100 B), so there is no payload transfer to pipeline and the
  two transports measure within noise of each other — which is exactly
  why the gateway's width/bytes routing floor (ops/devd_backend) sends
  such batches single-shot; the row documents that the floor is placed
  correctly for this shape rather than pretending a streamed win.
- cpu-node-hash (full bench only, reported): the same preimages through
  the host path the breaker falls back to (batched AVX ripemd160_x16
  when the native build is ready, per-node hashlib otherwise).
- live-daemon (auto-appends when a daemon already serves): the same
  node-hash shape against the real device (tunnel-window queue).

BENCH_STATETREE_SMOKE=1 shrinks sizes and skips the daemon rows for the
tier-1 gate; the smoke asserts but never writes BENCH_r13.json.
"""

from __future__ import annotations

import json
import os
import random
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_STATETREE_SMOKE", "") == "1"
TREE_N = int(os.environ.get("BENCH_STATETREE_N", "5000" if SMOKE else "50000"))
TREE_M = int(os.environ.get("BENCH_STATETREE_M", "100"))
STATE_SIZES = (
    [1000] if SMOKE
    else [int(x) for x in os.environ.get(
        "BENCH_STATETREE_SIZES", "2000,10000"
    ).split(",")]
)
CHURN = int(os.environ.get("BENCH_STATETREE_CHURN", "60"))
DELTA_MAX = float(os.environ.get("BENCH_STATETREE_DELTA_MAX", "0.5"))
NH_ITEMS = int(os.environ.get("BENCH_STATETREE_NH_ITEMS", "16384"))
NH_CHUNK = int(os.environ.get("BENCH_STATETREE_NH_CHUNK", "1024"))
NH_TRIALS = int(os.environ.get("BENCH_STATETREE_NH_TRIALS", "4"))
NH_SIM_RATE = float(os.environ.get("BENCH_STATETREE_SIM_RATE", "1000000"))


def _entries(n: int, seed: int = 1) -> dict[bytes, bytes]:
    rng = random.Random(seed)
    return {
        b"key-%08d" % rng.randrange(10 ** 12): b"value-%04d" % (i % 7919)
        for i in range(n)
    }


# -- commit-update vs full rebuild --------------------------------------------


def bench_commit_vs_rebuild() -> dict:
    from tendermint_tpu.statetree import VersionedTree

    entries = _entries(TREE_N)
    t0 = time.perf_counter()
    tree = VersionedTree.from_entries(entries, version=1)
    build_s = time.perf_counter() - t0

    rng = random.Random(7)
    keys = rng.sample(sorted(entries), TREE_M)
    update = {k: b"updated-" + k for k in keys}

    t0 = time.perf_counter()
    for k, v in update.items():
        tree.set(k, v)
    inc_root = tree.commit(2)
    incremental_s = time.perf_counter() - t0

    merged = {**entries, **update}
    t0 = time.perf_counter()
    rebuilt = VersionedTree.from_entries(merged, version=2)
    rebuild_s = time.perf_counter() - t0
    assert rebuilt.root_hash() == inc_root, "incremental commit diverged"

    return {
        "mode": "commit-vs-rebuild",
        "platform": "cpu",
        "keys": len(entries),
        "updated_keys": TREE_M,
        "initial_build_ms": round(build_s * 1e3, 1),
        "incremental_commit_ms": round(incremental_s * 1e3, 2),
        "full_rebuild_ms": round(rebuild_s * 1e3, 1),
        "dirty_nodes": tree.stats()["last_commit_nodes"],
        "speedup": round(rebuild_s / incremental_s, 1),
    }


# -- proof correctness --------------------------------------------------------


def bench_proofs() -> dict:
    from tendermint_tpu.merkle.statetree_proof import TreeProof
    from tendermint_tpu.statetree import VersionedTree

    entries = _entries(2000, seed=3)
    tree = VersionedTree.from_entries(entries, version=1)
    root = tree.root_hash()
    keys = sorted(entries)
    t0 = time.perf_counter()
    proofs = [tree.prove(k) for k in keys[:500]]
    prove_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    ok = all(p.verify(root) for p in proofs)
    verify_s = time.perf_counter() - t0
    assert ok, "membership proofs failed"
    absent = tree.prove(b"not-a-key")
    assert absent.value is None and absent.verify(root)
    sample = proofs[0]
    assert not TreeProof(sample.key, b"forged", sample.steps).verify(root)
    assert not sample.verify(b"\xee" * 20)
    assert not TreeProof(sample.key, None, sample.steps).verify(root)
    depth = sum(len(p.steps) for p in proofs) / len(proofs)
    return {
        "mode": "proof-correctness",
        "platform": "cpu",
        "keys": len(entries),
        "avg_proof_depth": round(depth, 1),
        "prove_us_each": round(prove_s / len(proofs) * 1e6, 1),
        "verify_us_each": round(verify_s / len(proofs) * 1e6, 1),
        "membership_ok": True,
        "absence_ok": True,
        "tampered_value_rejected": True,
        "wrong_root_rejected": True,
    }


# -- snapshot bytes + produce/restore: full vs delta --------------------------


def _grown_chain(n_keys: int):
    """A kvstore devchain seeding ~n_keys over 4 heights, then 4 more
    heights of small churn; snapshots full@4 and delta@8."""
    from tendermint_tpu.abci.apps.kvstore import KVStoreApp
    from tendermint_tpu.statesync import SnapshotProducer, SnapshotStore
    from tendermint_tpu.statesync.devchain import DevChain

    per_seed_height = max(n_keys // 4, 1)

    def tx_fn(h: int) -> list[bytes]:
        if h <= 4:
            return [
                b"seed-%07d=v%d" % (i, h)
                for i in range(per_seed_height * (h - 1), per_seed_height * h)
            ]
        txs = [b"seed-%07d=updated%d" % (i, h) for i in range(CHURN - 10)]
        txs += [b"fresh-%d-%d=x" % (h, i) for i in range(5)]
        txs += [b"rm:seed-%07d" % (per_seed_height * 4 - 1 - i) for i in range(5)]
        return txs

    chain = DevChain(KVStoreApp())
    store = SnapshotStore(tempfile.mkdtemp(prefix="bench-tree-snap-"))
    producer = SnapshotProducer(
        store, chain.app, chain.block_store, interval=4, keep_recent=8,
        chunk_size=65536, full_every=2,
    )
    for _ in range(8):
        chain.commit_block(tx_fn(chain.state.last_block_height + 1))
        producer.maybe_snapshot(chain.state)
    chain.build(1)
    return chain, store, producer


def bench_full_vs_delta(n_keys: int) -> dict:
    from tendermint_tpu.abci.apps.kvstore import KVStoreApp
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.rpc.light import LightClient
    from tendermint_tpu.statesync import Restorer, RestoreError
    from tendermint_tpu.statesync.snapshot import KIND_DELTA

    t0 = time.perf_counter()
    chain, store, producer = _grown_chain(n_keys)
    build_s = time.perf_counter() - t0
    full = store.load_manifest(4)
    delta = store.load_manifest(8)
    assert delta.kind == KIND_DELTA, "expected a delta at height 8"

    def fresh_restorer():
        lc = LightClient(
            chain.rpc_stub(), chain.genesis_doc.chain_id,
            chain.state.load_validators(1), trusted_height=0,
        )
        return Restorer(
            chain.genesis_doc, KVStoreApp(), MemDB(), BlockStore(MemDB()),
            light_client=lc,
        )

    def load(height):
        m = store.load_manifest(height)
        return m, [store.load_chunk(height, i) for i in range(m.chunks)]

    # delta-chain restore (full@4 then delta@8)
    r = fresh_restorer()
    t0 = time.perf_counter()
    state = r.restore_chain([load(4), load(8)])
    chain_restore_s = time.perf_counter() - t0
    assert state.last_block_height == 8
    assert r.app.app_hash == chain.app.tree.root_hash(8)

    # corrupt-chunk rejection on the delta link
    bad = fresh_restorer()
    m8, c8 = load(8)
    c8[-1] = bytes([c8[-1][0] ^ 0x01]) + c8[-1][1:]
    bad.restore(*load(4), seed=False)
    rejected = False
    try:
        bad.restore_delta(m8, c8)
    except RestoreError:
        rejected = True
    assert rejected, "corrupt delta chunk was NOT rejected"
    assert bad.app.info().last_block_height == 4, "corrupt delta mutated the app"

    return {
        "mode": "full-vs-delta",
        "platform": "cpu",
        "state_keys": len(chain.app.state),
        "churn_keys_per_interval": CHURN,
        "chain_build_s": round(build_s, 2),
        "full_bytes": full.total_bytes,
        "delta_bytes": delta.total_bytes,
        "delta_over_full": round(delta.total_bytes / full.total_bytes, 3),
        "full_produce_chunks": full.chunks,
        "delta_chunks": delta.chunks,
        "chain_restore_s": round(chain_restore_s, 3),
        "corrupt_delta_chunk_rejected": rejected,
        "deltas_applied": r.deltas_applied,
        "delta_entries_applied": r.delta_entries_applied,
    }


# -- streamed vs single-shot node hashing -------------------------------------


def _node_preimages(n: int) -> list[bytes]:
    """REAL tree-node hash preimages (the commit plane's workload),
    harvested by instrumenting a bulk build's hash batches."""
    from tendermint_tpu.statetree import VersionedTree

    collected: list[bytes] = []

    class _Tap:
        def part_leaf_hashes(self, chunks):
            from tendermint_tpu.crypto.hashing import ripemd160

            collected.extend(chunks)
            return [ripemd160(c) for c in chunks]

    size = max(n // 2, 1024)
    VersionedTree.from_entries(_entries(size, seed=11), version=1, hasher=_Tap())
    while len(collected) < n:
        collected.extend(collected[: n - len(collected)])
    return collected[:n]


def bench_sim_node_hash() -> dict:
    from benches.bench_statesync import (
        _measure_chunk_verify,
        _spawn_daemon,
        _wait_held,
    )
    from tendermint_tpu import devd

    items = _node_preimages(NH_ITEMS)
    proc, sock, err_path = _spawn_daemon(
        {"TENDERMINT_DEVD_SIM_RATE": str(int(NH_SIM_RATE))}
    )
    try:
        client = devd.DevdClient(sock)
        _wait_held(client, proc, err_path, 60.0)
        row = _measure_chunk_verify(client, items, NH_CHUNK, NH_TRIALS)
        row.update(
            mode="sim-node-hash", platform="sim",
            sim_device_items_per_sec=NH_SIM_RATE,
            note="items are real statetree node preimages",
        )
        client.shutdown()
        client.close()
    finally:
        try:
            proc.wait(timeout=15)
        except Exception:  # noqa: BLE001
            proc.kill()
    return row


def bench_cpu_node_hash() -> dict:
    from tendermint_tpu import native
    from tendermint_tpu.crypto.hashing import ripemd160

    items = _node_preimages(NH_ITEMS)
    mb = sum(len(it) for it in items) / 1e6
    t0 = time.perf_counter()
    loop = [ripemd160(it) for it in items]
    loop_s = time.perf_counter() - t0
    row = {
        "mode": "cpu-node-hash",
        "platform": "cpu",
        "items": len(items),
        "loop_mb_per_sec": round(mb / loop_s, 2),
        "loop_ms": round(loop_s * 1000, 1),
        "native_ready": bool(native.ready()),
    }
    if native.ready():
        t0 = time.perf_counter()
        batched = native.ripemd160_batch(items)
        batch_s = time.perf_counter() - t0
        assert batched == loop, "native batch diverged from hashlib"
        row["native_batch_mb_per_sec"] = round(mb / batch_s, 2)
        row["native_batch_ms"] = round(batch_s * 1000, 1)
        row["native_speedup"] = round(loop_s / batch_s, 2)
    return row


def bench_live_daemon() -> dict | None:
    from benches.bench_statesync import _measure_chunk_verify
    from tendermint_tpu import devd

    live = devd.available(timeout=3.0)
    if live is None:
        return None
    client = devd.DevdClient()
    row = _measure_chunk_verify(
        client, _node_preimages(NH_ITEMS), NH_CHUNK, max(2, NH_TRIALS - 1)
    )
    row.update(platform=live.get("platform"), mode="live-daemon")
    client.close()
    return row


def main() -> None:
    rows = [bench_commit_vs_rebuild(), bench_proofs()]
    delta_rows = [bench_full_vs_delta(n) for n in STATE_SIZES]
    rows.extend(delta_rows)
    sim = None
    if not SMOKE:
        sim = bench_sim_node_hash()
        rows.append(sim)
        rows.append(bench_cpu_node_hash())
        live = bench_live_daemon()
        if live is not None:
            rows.append(live)

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": (
            "statetree: incremental commit vs rebuild, proof correctness, "
            "delta vs full snapshot bytes, streamed vs single-shot node "
            "hashing"
        ),
        "delta_over_full_max_asserted": DELTA_MAX,
        "incremental_commit_min_asserted": 2.0,
        "smoke": SMOKE,
        "rows": rows,
        "note": (
            "cpu/sim rows are chip-free; the live-daemon row auto-appends "
            "when a daemon serves (tunnel-window queue, ROADMAP)"
        ),
    }
    # assert BEFORE writing: a below-floor run must fail loudly without
    # replacing the recorded artifact
    final = delta_rows[-1]
    assert final["delta_over_full"] <= DELTA_MAX, (
        f"delta snapshot is {final['delta_over_full']}x of full "
        f"(> {DELTA_MAX} ceiling) at {final['state_keys']} keys"
    )
    inc = rows[0]
    assert inc["speedup"] >= 2.0, (
        f"incremental commit only {inc['speedup']}x over full rebuild"
    )
    # sim-node-hash asserts digest PARITY inside _measure_chunk_verify;
    # the stream/single ratio is recorded unasserted (tiny preimages
    # have no payload to pipeline — see the module docstring)
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r13.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "statetree_incremental_commit_vs_rebuild",
        "value": inc["speedup"],
        "unit": "x",
        "delta_over_full": final["delta_over_full"],
        "node_hash_streamed_speedup": sim["speedup"] if sim else None,
        "corrupt_delta_chunk_rejected": final["corrupt_delta_chunk_rejected"],
        "platform": "cpu" if SMOKE else "cpu+sim",
        "smoke": SMOKE,
    }))


if __name__ == "__main__":
    main()
