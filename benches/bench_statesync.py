"""BENCH_r10: state-sync snapshot subsystem (docs/state-sync.md).

Rows (all chip-free except the auto-appended live-daemon row):

- round-trip (ALWAYS, asserted): one producer -> restore cycle on a real
  signedkv chain, light-verified end to end, with an injected corrupt
  chunk REJECTED mid-path — the correctness gate the Makefile's
  `statesync-smoke` runs in tier 1.
- restore-vs-replay (ALWAYS, reported): cold-start cost for a fresh node
  joining an N-block signedkv chain — fast-sync-style replay (commit
  verify + execute + part hashing per height, the pre-round-10 only way
  in) vs snapshot restore (light walk to H+1 + batched chunk digests +
  wholesale apply). Restore does one commit verify per height and NO
  execution, so the gap widens with chain length / tx weight.
- sim-chunk-verify (ALWAYS, asserted >= BENCH_STATESYNC_MIN, default
  1.3x): the restore path's bulk hash workload — per-chunk RIPEMD-160
  digesting against a sim-device daemon (devd._SimHasher), streamed
  (`hash_stream`, the gateway's windowed batch-verify route) vs
  single-shot (`hash_batch`, one monolithic pickled round trip).
- live-daemon (auto-appends when a daemon already serves): the same
  chunk-verify shape against the real device, joining the tunnel-window
  queue (ROADMAP r06/r07 note).

BENCH_STATESYNC_SMOKE=1 shrinks sizes for the tier-1 gate; the smoke
asserts but never writes BENCH_r10.json (bench_partset's convention).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_STATESYNC_SMOKE", "") == "1"
N_BLOCKS = int(os.environ.get("BENCH_STATESYNC_BLOCKS", "80" if SMOKE else "300"))
TXS_PER_BLOCK = int(os.environ.get("BENCH_STATESYNC_TXS", "2"))
CHUNK_SIZE = int(os.environ.get("BENCH_STATESYNC_CHUNK_BYTES", "16384"))
# the chunk-verify row keeps full size even in smoke: the streamed win
# grows with batch width, and the smoke ASSERTS the 1.3x floor. A
# 4096x1024B batch ran ~1.45x idle but dipped to 1.28x on a loaded host
# (tier-1 runs the smokes back to back) and 8192 still swung 1.34-2.5x;
# 16384 items / 1024-wide windows (bench_partset's proven shape) hold a
# tight 2.4-2.6x — fixed overheads amortize, so host noise stops
# dominating the ratio
CV_ITEMS = int(os.environ.get("BENCH_STATESYNC_CV_ITEMS", "16384"))
CV_ITEM_BYTES = int(os.environ.get("BENCH_STATESYNC_CV_ITEM_BYTES", "1024"))
CV_CHUNK = int(os.environ.get("BENCH_STATESYNC_CV_CHUNK", "1024"))
CV_TRIALS = int(os.environ.get("BENCH_STATESYNC_CV_TRIALS", "3" if SMOKE else "4"))
CV_SIM_RATE = float(os.environ.get("BENCH_STATESYNC_SIM_RATE", "1000000"))
MIN_SPEEDUP = float(os.environ.get("BENCH_STATESYNC_MIN", "1.3"))


# -- the chain both rows share ------------------------------------------------


def _build() -> tuple:
    """(chain, snap_store, manifest, chunks): an N-block signedkv chain
    with a snapshot at height N and one block past it (the manifest
    binds to header H+1)."""
    from tendermint_tpu.statesync import SnapshotProducer, SnapshotStore
    from tendermint_tpu.statesync.devchain import build_signedkv_chain

    t0 = time.perf_counter()
    chain = build_signedkv_chain(N_BLOCKS, txs_per_block=TXS_PER_BLOCK)
    build_s = time.perf_counter() - t0
    store = SnapshotStore(tempfile.mkdtemp(prefix="bench-snap-"))
    producer = SnapshotProducer(
        store, chain.app, chain.block_store, chunk_size=CHUNK_SIZE
    )
    height = producer.snapshot(chain.state)
    chain.build(1)
    manifest = store.load_manifest(height)
    chunks = [store.load_chunk(height, i) for i in range(manifest.chunks)]
    return chain, store, manifest, chunks, build_s


def _fresh_restorer(chain):
    from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.rpc.light import LightClient
    from tendermint_tpu.statesync import Restorer

    lc = LightClient(
        chain.rpc_stub(), chain.genesis_doc.chain_id,
        chain.state.load_validators(1), trusted_height=0,
    )
    return Restorer(
        chain.genesis_doc, SignedKVStoreApp(), MemDB(), BlockStore(MemDB()),
        light_client=lc,
    )


# -- round-trip correctness gate ----------------------------------------------


def bench_round_trip(chain, manifest, chunks) -> dict:
    """Restore once (must succeed, byte-exact), then replay with one
    corrupt chunk injected (must be REJECTED with nothing applied)."""
    from tendermint_tpu.statesync import RestoreError

    restorer = _fresh_restorer(chain)
    t0 = time.perf_counter()
    state = restorer.restore(manifest, chunks)
    restore_s = time.perf_counter() - t0
    assert state.last_block_height == manifest.height
    assert state.app_hash == manifest.app_hash
    assert restorer.app.info().last_block_app_hash == chain.app.app_hash

    bad_restorer = _fresh_restorer(chain)
    evil = list(chunks)
    evil[len(evil) // 2] = (
        bytes([evil[len(evil) // 2][0] ^ 0x01]) + evil[len(evil) // 2][1:]
    )
    rejected = False
    try:
        bad_restorer.restore(manifest, evil)
    except RestoreError:
        rejected = True
    assert rejected, "corrupt chunk was NOT rejected"
    assert bad_restorer.app.info().last_block_height == 0, (
        "corrupt restore mutated the app"
    )
    return {
        "mode": "round-trip",
        "platform": "cpu",
        "blocks": N_BLOCKS,
        "chunks": manifest.chunks,
        "snapshot_bytes": manifest.total_bytes,
        "restore_ms": round(restore_s * 1e3, 1),
        "corrupt_chunk_rejected": rejected,
    }


# -- restore vs fast-sync replay ----------------------------------------------


def bench_restore_vs_replay(chain, manifest, chunks) -> dict:
    import threading

    from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.proxy.app_conn import AppConnConsensus
    from tendermint_tpu.state.execution import apply_block
    from tendermint_tpu.state.state import State
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.services import MockMempool

    height = manifest.height
    part_size = chain.state.params().block_gossip.block_part_size_bytes

    # -- replay: what fast sync does per height, minus the transport —
    # commit verify + part-set rebuild + execute through the app
    app = SignedKVStoreApp()
    state = State.get_state(MemDB(), chain.genesis_doc)
    store = BlockStore(MemDB())
    proxy = AppConnConsensus(LocalClient(app, threading.RLock()))
    t0 = time.perf_counter()
    for h in range(1, height + 1):
        block = chain.block_store.load_block(h)
        parts = block.make_part_set(part_size)
        commit = chain.block_store.load_block_commit(h)
        state.validators.verify_commit(
            state.chain_id, BlockID(block.hash(), parts.header()), h, commit
        )
        store.save_block(block, parts, chain.block_store.load_seen_commit(h))
        apply_block(state, None, proxy, block, parts.header(), MockMempool())
    replay_s = time.perf_counter() - t0
    assert state.last_block_height == height
    assert state.app_hash == manifest.app_hash

    # -- restore: light walk + batched chunk digests + wholesale apply
    restorer = _fresh_restorer(chain)
    t0 = time.perf_counter()
    restorer.verify_manifest(manifest)
    walk_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    restored = restorer.restore(manifest, chunks)
    apply_s = time.perf_counter() - t0
    restore_s = walk_s + apply_s
    assert restored.app_hash == state.app_hash, "restore diverged from replay"

    return {
        "mode": "restore-vs-replay",
        "platform": "cpu",
        "blocks": height,
        "txs_per_block": TXS_PER_BLOCK,
        "replay_s": round(replay_s, 3),
        "restore_s": round(restore_s, 3),
        "light_walk_s": round(walk_s, 3),
        "restore_apply_s": round(apply_s, 3),
        "speedup": round(replay_s / restore_s, 2),
        "replay_blocks_per_sec": round(height / replay_s, 1),
    }


# -- streamed vs single-shot chunk verification -------------------------------


def _spawn_daemon(extra_env: dict):
    run_dir = tempfile.mkdtemp(prefix="bench-ssd-")
    sock = os.path.join(run_dir, "devd.sock")
    env = {
        **os.environ,
        "TENDERMINT_DEVD_SOCK": sock,
        "TENDERMINT_DEVD_ACCEPT_CPU": "1",
        "TENDERMINT_DEVD_EXIT_ON_TERM": "1",
        **extra_env,
    }
    # stderr to a file: a chatty daemon on a pipe nobody drains would
    # block and hang the smoke gate (bench_partset learned this)
    err_path = os.path.join(run_dir, "daemon.err")
    with open(err_path, "wb") as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.devd"],
            env=env, cwd=ROOT,
            stdout=subprocess.DEVNULL, stderr=err_f,
        )
    return proc, sock, err_path


def _wait_held(client, proc, err_path: str, deadline_s: float) -> None:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            try:
                with open(err_path, "rb") as f:
                    err = f.read()
            except OSError:
                err = b""
            raise RuntimeError(f"daemon died: {err[-2000:]!r}")
        try:
            if client.ping(timeout=2.0).get("held"):
                return
        except Exception:  # noqa: BLE001 — still starting
            pass
        time.sleep(0.5)
    raise RuntimeError("daemon never reached serving state")


def _measure_chunk_verify(client, items, chunk: int, trials: int) -> dict:
    """Digest `items` (snapshot-chunk-shaped payloads) both ways,
    best-of-`trials` each, alternated. Single-shot = one monolithic
    pickled request; streamed = the windowed chunk frames the restore
    path's batch verify rides."""
    n = len(items)
    client.hash_batch(items[: min(n, 256)])  # connection + import warm
    client.hash_stream(items[: min(n, 256)], chunk=max(chunk // 8, 32))
    single_best = stream_best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        r1 = client.hash_batch(items)
        single_best = min(single_best, time.perf_counter() - t0)
        t0 = time.perf_counter()
        r2 = client.hash_stream(items, chunk=chunk)
        stream_best = min(stream_best, time.perf_counter() - t0)
        assert r1 == r2, "streamed digests diverge from single-shot"
    mb = sum(len(it) for it in items) / 1e6
    return {
        "chunks": n,
        "chunk_bytes": len(items[0]),
        "stream_window": chunk,
        "single_shot_mb_per_sec": round(mb / single_best, 2),
        "streamed_mb_per_sec": round(mb / stream_best, 2),
        "single_shot_ms": round(single_best * 1000, 1),
        "streamed_ms": round(stream_best * 1000, 1),
        "speedup": round(single_best / stream_best, 3),
    }


def _chunk_items() -> list[bytes]:
    return [bytes([i % 251]) * CV_ITEM_BYTES for i in range(CV_ITEMS)]


def bench_sim_chunk_verify() -> dict:
    from tendermint_tpu import devd

    proc, sock, err_path = _spawn_daemon(
        {"TENDERMINT_DEVD_SIM_RATE": str(int(CV_SIM_RATE))}
    )
    try:
        client = devd.DevdClient(sock)
        _wait_held(client, proc, err_path, 60.0)
        row = _measure_chunk_verify(client, _chunk_items(), CV_CHUNK, CV_TRIALS)
        row.update(
            mode="sim-chunk-verify", platform="sim",
            sim_device_items_per_sec=CV_SIM_RATE,
        )
        client.shutdown()
        client.close()
    finally:
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
    return row


def bench_live_daemon() -> dict | None:
    """The chunk-verify shape against an ALREADY-serving daemon — the
    live-chip row, auto-appended whenever a tunnel window is open."""
    from tendermint_tpu import devd

    live = devd.available(timeout=3.0)
    if live is None:
        return None
    client = devd.DevdClient()
    row = _measure_chunk_verify(
        client, _chunk_items(), CV_CHUNK, max(2, CV_TRIALS - 1)
    )
    row.update(platform=live.get("platform"), mode="live-daemon")
    client.close()
    return row


def main() -> None:
    chain, _store, manifest, chunks, build_s = _build()
    rows = [
        bench_round_trip(chain, manifest, chunks),
        bench_restore_vs_replay(chain, manifest, chunks),
    ]
    sim = bench_sim_chunk_verify()
    rows.append(sim)
    live = bench_live_daemon()
    if live is not None:
        rows.append(live)

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": (
            "statesync: restore vs fast-sync replay + streamed vs "
            "single-shot chunk verification"
        ),
        "min_speedup_asserted": MIN_SPEEDUP,
        "smoke": SMOKE,
        "chain_build_s": round(build_s, 2),
        "rows": rows,
        "note": (
            "round-trip / restore-vs-replay / sim-chunk-verify rows are "
            "chip-free; the live-daemon row auto-appends when a daemon "
            "serves (tunnel-window queue, ROADMAP)"
        ),
    }
    # assert BEFORE writing: a below-floor run must fail loudly without
    # replacing the recorded artifact
    assert sim["speedup"] >= MIN_SPEEDUP, (
        f"streamed chunk verify {sim['speedup']}x < {MIN_SPEEDUP}x floor"
    )
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r10.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "statesync_restore_vs_replay",
        "value": rows[1]["speedup"],
        "unit": "x",
        "replay_s": rows[1]["replay_s"],
        "restore_s": rows[1]["restore_s"],
        "chunk_verify_streamed_speedup": sim["speedup"],
        "corrupt_chunk_rejected": rows[0]["corrupt_chunk_rejected"],
        "platform": "cpu+sim",
        "smoke": SMOKE,
    }))


if __name__ == "__main__":
    main()
