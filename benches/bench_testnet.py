"""BASELINE config 1: 4-validator in-process testnet, kvstore ABCI app.

End-to-end: four real nodes (consensus + mempool reactors over pipe
switches) commit tx-bearing blocks; measures committed blocks/sec and
then asserts BYTE-IDENTICAL commit artifacts between the CPU and TPU
paths: for every committed block, the tx-merkle root, the part-set
header, and the commit verification verdicts are recomputed through the
TPU gateway and compared against the CPU reference.

Prints ONE JSON line. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.jitcache import enable as _enable_jit_cache
from tendermint_tpu.jitcache import platform_label

_enable_jit_cache()

N_BLOCKS = int(os.environ.get("BENCH_N_BLOCKS", "8"))
N_TXS = int(os.environ.get("BENCH_N_TXS", "64"))


def main() -> None:
    from tendermint_tpu.crypto import ed25519 as ed_cpu
    from tendermint_tpu.merkle.simple import simple_hash_from_hashes
    from tendermint_tpu.ops.gateway import Hasher, Verifier
    from tendermint_tpu.types import tx as tx_types
    from tests.test_reactors import (
        make_genesis,
        make_node,
        start_consensus_net,
        stop_net,
        wait_until,
    )
    from tendermint_tpu.abci.apps.kvstore import KVStoreApp

    nodes, switches = start_consensus_net(4, app_factory=KVStoreApp)
    t0 = time.perf_counter()
    try:
        for i in range(N_TXS):
            nodes[0].mempool.check_tx(b"bench%d=v%d" % (i, i))
        assert wait_until(
            lambda: all(n.store.height() >= N_BLOCKS for n in nodes), timeout=120
        ), [n.store.height() for n in nodes]
        elapsed = time.perf_counter() - t0

        # -- byte-identical commit artifacts: CPU vs TPU ------------------
        # honor an explicit disable (run_all pins it on a dead tunnel);
        # the parity assertions hold either way — CPU fallback must be
        # byte-identical by design
        tpu_on = os.environ.get("TENDERMINT_TPU_DISABLE", "") != "1"
        verifier = Verifier(min_tpu_batch=1, use_tpu=tpu_on)
        hasher = Hasher(min_tpu_batch=1, use_tpu=tpu_on)
        part_size = nodes[0].state.params().block_gossip.block_part_size_bytes
        checked_sigs = 0
        for h in range(1, N_BLOCKS + 1):
            blocks = [n.store.load_block(h) for n in nodes]
            assert all(
                b.hash() == blocks[0].hash() for b in blocks
            ), f"nodes disagree at height {h}"
            blk = blocks[0]
            # tx root: CPU reference vs gateway kernel
            txs = blk.data.txs
            if txs:
                cpu_root = simple_hash_from_hashes(
                    [tx_types.tx_hash(t) for t in txs]
                )
                assert hasher.tx_merkle_root(list(txs)) == cpu_root == blk.header.data_hash
            # part-set header: CPU vs gateway kernel
            cpu_ps = blk.make_part_set(part_size)
            tpu_ps = blk.make_part_set(part_size, hasher=hasher.part_leaf_hashes)
            assert cpu_ps.header() == tpu_ps.header()
            # commit signatures: kernel verdicts == CPU verdicts
            commit = nodes[0].store.load_block_commit(h)
            if commit is None:
                continue
            vs = nodes[0].state.validators
            items = [
                (
                    vs.validators[i].pub_key.raw,
                    pc.sign_bytes(nodes[0].state.chain_id),
                    pc.signature.raw,
                )
                for i, pc in enumerate(commit.precommits)
                if pc is not None
            ]
            tpu_ok = verifier.verify_batch(items)
            cpu_ok = [ed_cpu.verify(p, m, s) for p, m, s in items]
            assert tpu_ok == cpu_ok and all(tpu_ok), f"verdict mismatch at {h}"
            checked_sigs += len(items)
    finally:
        stop_net(nodes, switches)

    print(
        json.dumps(
            {
                "metric": "testnet_blocks_per_sec",
                "value": round(N_BLOCKS / elapsed, 2),
                "unit": "blocks/s",
                "vs_baseline": 1.0,  # parity run: identical artifacts asserted
                "detail": {
                    "nodes": 4,
                    "app": "kvstore",
                    "blocks": N_BLOCKS,
                    "txs": N_TXS,
                    "commit_sigs_checked": checked_sigs,
                    "platform": platform_label(),
                    "parity": "byte-identical (tx roots, part headers, verdicts)",
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
