"""Fleet observability bench + smoke (round 15): the cross-node
measurement substrate must actually measure — and must not tax the
planes it watches.

Rows (written to BENCH_r15.json on full runs):

- fleet_timeline:  boot a 4-node REAL-TCP net (netchaos_common.ChaosNet:
                   full nodes, in-repo SecretConnection on every link),
                   then reconstruct the per-height cross-node timeline
                   from NOTHING but GET /metrics + consensus_trace
                   scrapes (ops/fleet.py): proposer->peer propagation
                   lag, quorum-formation time, commit skew. Asserted:
                   >= 2 heights reconstructed with all 4 nodes
                   reporting, skew/quorum data present.
- partition_health: the netchaos partition arm on the scraped surface —
                   partition {3}, /health flips degraded (detect seconds
                   recorded), heal, /health recovers ok (recover seconds
                   recorded), the outage visible in the quorum surface.
- p2p_overhead:    computed upper bound on the NEW per-peer/arrival
                   instrumentation during the live window: (instrument
                   events the net actually executed) x (3x-margined
                   micro-measured per-event cost) / window wall — the
                   BENCH_r11 method. Asserted < 2%.
- gate_overhead:   the BENCH_r11 signed-burst gate guard re-asserted
                   with the round-15 families registered (imported from
                   benches/bench_telemetry.py, reduced shape). Asserted
                   < 2%: registering new families must cost the mempool
                   hot path nothing.

BENCH_FLEET_SMOKE=1 keeps the windows tight for the tier-1 gate
(`make fleet-smoke`, ~40 s); the smoke asserts but never writes (the
bench_partset convention). Prints ONE JSON line. Run from the repo root.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

SMOKE = os.environ.get("BENCH_FLEET_SMOKE", "") == "1"
N_NODES = int(os.environ.get("BENCH_FLEET_NODES", "4"))
WINDOW_S = float(os.environ.get("BENCH_FLEET_WINDOW_S",
                                "6" if SMOKE else "12"))
LAST = int(os.environ.get("BENCH_FLEET_LAST", "8"))
MAX_OVERHEAD_PCT = float(os.environ.get("BENCH_FLEET_MAX_OVERHEAD_PCT",
                                        "2.0"))

# hermetic like tests/conftest.py: never dial a production daemon, pin
# the CPU platform before jax loads — and tighten the health/reconnect
# cadence so the partition arm runs in bench time
os.environ.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
os.environ.setdefault("TENDERMINT_TPU_PLATFORM", "cpu")
os.environ.setdefault("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", "3.0")
os.environ.setdefault("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "1e9")
os.environ.setdefault("TENDERMINT_HEALTH_MIN_PEERS", "1")
# reduced signed-burst shape for the imported BENCH_r11 gate guard
os.environ.setdefault("BENCH_TELEMETRY_SMOKE", "1")
os.environ.setdefault("BENCH_TELEMETRY_TXS", "1024")
os.environ.setdefault("BENCH_TELEMETRY_REPEATS", "2")


def _median(vals, default=None):
    vals = [v for v in vals if v is not None]
    return round(statistics.median(vals), 6) if vals else default


def bench_fleet_timeline(net, urls) -> tuple[dict, dict]:
    """Scrape the live net; reconstruct and assert the timeline."""
    from tendermint_tpu.ops import fleet

    t0 = time.perf_counter()
    snapshot = fleet.collect(urls, last=LAST)
    scrape_s = time.perf_counter() - t0
    for url, entry in snapshot.items():
        assert "error" not in entry, (url, entry.get("error"))
        assert entry["health"]["status"] in ("ok", "degraded"), entry["health"]
    rows = fleet.build_timeline(
        {u: e["traces"] for u, e in snapshot.items()}, last=LAST
    )
    full = [r for r in rows if r["nodes_reporting"] == N_NODES]
    assert len(full) >= 2, (
        f"timeline must reconstruct >= 2 heights on all {N_NODES} nodes: "
        f"{[(r['height'], r['nodes_reporting']) for r in rows]}"
    )
    skews = [r["commit_skew_s"] for r in full]
    quorums = [r["precommit_quorum_s_max"] for r in full]
    lags = [r["propagation_lag_s"] for r in full]
    assert any(s is not None for s in skews)
    assert any(q is not None for q in quorums)
    return {
        "heights_reconstructed": len(rows),
        "heights_all_nodes": len(full),
        "scrape_all_nodes_s": round(scrape_s, 3),
        "propagation_lag_s_median": _median(lags),
        "precommit_quorum_s_median": _median(quorums),
        "commit_skew_s_median": _median(skews),
        "commit_skew_s_max": max((s for s in skews if s is not None),
                                 default=None),
    }, snapshot


def bench_partition_health(net, urls) -> dict:
    """The netchaos partition arm, asserted purely off scrapes."""
    from tendermint_tpu.ops import fleet
    from netchaos_common import wait_until

    victim = urls[N_NODES - 1]

    def status(url):
        return fleet.fetch_health(url)["status"]

    assert wait_until(lambda: all(status(u) == "ok" for u in urls),
                      timeout=60), [status(u) for u in urls]
    q_sum0 = fleet.metric_value(
        fleet.fetch_metrics(victim), "consensus_quorum_seconds_sum",
        {"phase": "precommit"}, default=0.0,
    )

    net.partition({N_NODES - 1})
    t0 = time.perf_counter()
    assert wait_until(lambda: status(victim) == "degraded", timeout=45), (
        "partition never flipped /health degraded"
    )
    detect_s = time.perf_counter() - t0
    m = fleet.fetch_metrics(victim)
    peers = (fleet.metric_value(m, "p2p_peers_outbound", default=0)
             + fleet.metric_value(m, "p2p_peers_inbound", default=0))
    assert peers == 0, "severed links must show in the scraped peer gauges"
    # hold the partition until the LIVENESS signal engages too (the
    # peers check flips instantly; the quorum-spike assertion below
    # needs the stall to actually span the height-age budget)
    assert wait_until(
        lambda: fleet.fetch_health(victim)["checks"]["height_age"][
            "status"] == "degraded",
        timeout=45,
    ), "height age never crossed the degraded budget under partition"

    net.heal()
    t0 = time.perf_counter()
    assert wait_until(lambda: status(victim) == "ok", timeout=90), (
        "heal never recovered /health"
    )
    recover_s = time.perf_counter() - t0
    q_sum1 = fleet.metric_value(
        fleet.fetch_metrics(victim), "consensus_quorum_seconds_sum",
        {"phase": "precommit"}, default=0.0,
    )
    traces = fleet.fetch_traces(victim, last=10)
    spiked = (q_sum1 - q_sum0 > 2.0) or any(
        t["wall_s"] > 2.5 for t in traces
    )
    assert spiked, "the outage must land in the quorum/trace surface"
    return {
        "detect_degraded_s": round(detect_s, 2),
        "heal_recover_s": round(recover_s, 2),
        "quorum_sum_delta_s": round(q_sum1 - q_sum0, 3),
    }


def bench_p2p_overhead(snap0, snap1, window_s, observe_row) -> dict:
    """BENCH_r11-method bound on the round-15 instrumentation during the
    live window: count the instrument events the net executed (scraped
    counter deltas), multiply by the 3x-margined per-event micro cost,
    divide by the window wall."""
    from tendermint_tpu.ops import fleet

    def total(snapshot, name):
        return sum(
            fleet.metric_value(e["metrics"], name, default=0.0) or 0.0
            for e in snapshot.values() if "metrics" in e
        )

    def delta(name):
        return max(0.0, total(snap1, name) - total(snap0, name))

    msgs = (delta("p2p_peer_send_msgs_total")
            + delta("p2p_peer_recv_msgs_total"))
    # packets ~ bytes/1024, floored by whole messages; each packet costs
    # <= 2 child increments (bytes + eof-msg / bytes + queue sample)
    packets = max(
        (delta("p2p_peer_send_bytes_total")
         + delta("p2p_peer_recv_bytes_total")) / 1024.0,
        msgs,
    )
    gossip = (delta("p2p_peer_vote_gossip_picks_total")
              + delta("p2p_peer_vote_gossip_sends_total")
              + delta("p2p_peer_vote_gossip_send_failures_total")
              + delta("p2p_peer_catchup_commits_total"))
    arrivals = delta("consensus_quorum_seconds_count") + delta(
        "consensus_first_part_seconds_count"
    )
    import bench_telemetry

    events = 2.0 * packets + msgs + gossip + arrivals
    per_event_ns = bench_telemetry.per_event_cost_ns(observe_row)
    overhead_pct = events * per_event_ns / (window_s * 1e9) * 100.0
    row = {
        "window_s": round(window_s, 2),
        "instrument_events_est": int(events),
        "per_event_cost_ns_3x_margin": round(per_event_ns, 1),
        "overhead_pct_bound": round(overhead_pct, 4),
        "max_overhead_pct_asserted": MAX_OVERHEAD_PCT,
    }
    assert overhead_pct < MAX_OVERHEAD_PCT, (
        f"round-15 p2p instrumentation bound {overhead_pct:.3f}% "
        f"(floor {MAX_OVERHEAD_PCT}%): {row}"
    )
    return row


def main() -> None:
    from netchaos_common import ChaosNet
    from tendermint_tpu.ops import fleet

    # micro costs + the signed-burst gate guard ride bench_telemetry's
    # machinery (reduced shape via the env defaults above)
    import bench_telemetry

    observe_row = bench_telemetry.bench_observe_ns()

    root = tempfile.mkdtemp(prefix="bench-fleet-")
    net = ChaosNet(N_NODES, root)
    rows: dict = {}
    try:
        t0 = time.perf_counter()
        net.start()
        assert net.wait_height(2, timeout=120), net.heights()
        boot_s = time.perf_counter() - t0
        urls = [f"127.0.0.1:{n.rpc_port()}" for n in net.nodes]

        snap0 = fleet.collect(urls, last=1)
        t0 = time.perf_counter()
        target = max(net.heights()) + max(2, int(WINDOW_S))
        assert net.wait_height(target, timeout=WINDOW_S * 20), net.heights()
        window_s = time.perf_counter() - t0

        timeline_row, snap1 = bench_fleet_timeline(net, urls)
        timeline_row["boot_s"] = round(boot_s, 2)
        rows["fleet_timeline"] = timeline_row
        rows["p2p_overhead"] = bench_p2p_overhead(
            snap0, snap1, window_s, observe_row
        )
        rows["partition_health"] = bench_partition_health(net, urls)
    finally:
        net.stop()

    rows["gate_overhead"] = bench_telemetry.bench_gate_overhead(observe_row)

    record = {
        "bench": "fleet",
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": "cpu",
        "smoke": SMOKE,
        "rows": rows,
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r15.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "fleet_heights_reconstructed_all_nodes",
        "value": rows["fleet_timeline"]["heights_all_nodes"],
        "unit": "heights",
        "vs_baseline": 1.0,  # observability substrate: no reference exists
        "detail": {
            "commit_skew_s_median":
                rows["fleet_timeline"]["commit_skew_s_median"],
            "precommit_quorum_s_median":
                rows["fleet_timeline"]["precommit_quorum_s_median"],
            "partition_detect_s":
                rows["partition_health"]["detect_degraded_s"],
            "heal_recover_s": rows["partition_health"]["heal_recover_s"],
            "p2p_overhead_pct_bound":
                rows["p2p_overhead"]["overhead_pct_bound"],
            "gate_overhead_pct_bound":
                rows["gate_overhead"]["overhead_pct_bound"],
            "smoke": SMOKE,
        },
    }))


if __name__ == "__main__":
    main()
