"""Localnet-at-scale bench (round 20): consensus cadence, duplicate-vote
redundancy, and gossip bytes/height of a REAL PROCESS fleet vs node
count (docs/localnet.md).

Every prior multi-node bench ran nodes in-process (one interpreter, one
GIL). This one boots `ops/localnet` fleets — real `tendermint_tpu.cli
node` processes on loopback, peered through netfaults link proxies —
and reads everything off the public scrape surface.

Rows (full run):
- scale:n=10 / n=25 / n=50: heights/s, fleet duplicate-vote ratio
  (consensus_vote_duplicates / consensus_vote_accepted — the 2N*N
  redundancy number the has-vote dedup engineers down), gossip
  bytes/height, per-height byte-identity across ALL nodes. The 50-node
  row runs under the `continental` WAN profile (seeded per-link
  latency/loss/bandwidth) on a ring topology — the hundreds-of-nodes
  shape on one box.
- dedup_off:n=10: the SAME 10-node fleet with gossip_dedup=false (the
  pre-round-20 gossip); the duplicate-vote ratio is asserted strictly
  WORSE than the dedup-on row — the measurable the tentpole claims.
- dedup_ab:ring:n=10 (round 21): the same A/B on an explicit RING —
  the sparse hundreds-of-nodes shape where votes arrive mostly by
  relay; dedup-on asserted strictly better there too.
- partition_heal:n=10: a netchaos-style fault at process scale — 1/3
  minority severed, majority keeps committing, heal, full-fleet
  byte-identity.

Asserted floors (chip-free — this gates `make localnet-smoke` in tier1):
- every fleet converges byte-identically (the scenario asserts it)
- the duplicate-vote ratio is read from live scrapes (accepted > 0)
- full run: dedup-on ratio < dedup-off ratio at n=10

BENCH_LOCALNET_SMOKE=1 shrinks to one 5-node converge run (~60 s) for
the tier-1 gate. Prints ONE JSON line like the other benches; writes
BENCH_r20.json on full runs. Run from the repo root:
python benches/bench_localnet.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_LOCALNET_SMOKE", "") == "1"
# scale ladder is env-tunable so a crowded box can shrink it without
# editing the bench
SCALES = (
    [(5, 3, "")]
    if SMOKE
    else [(10, 5, ""), (25, 4, ""), (50, 3, "continental")]
)


def main() -> None:
    os.environ.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
    os.environ.setdefault("TENDERMINT_TPU_PLATFORM", "cpu")

    from tendermint_tpu.ops.localnet import LocalnetSpec, run_scenario

    rows = []
    port = 47400
    ratio_at_10 = None

    def spec_for(n: int, wan: str, dedup: bool = True,
                 topology: str = "") -> LocalnetSpec:
        nonlocal port
        root = tempfile.mkdtemp(prefix=f"bench-localnet-{n}-")
        s = LocalnetSpec(
            n=n, root=root, seed=20, base_port=port, wan=wan,
            gossip_dedup=dedup, topology=topology,
        )
        # fleets run serially but TIME_WAIT lingers: each gets its own
        # port range
        port += 2 * n + 10
        return s

    # -- the scale ladder ---------------------------------------------------
    for n, heights, wan in SCALES:
        t0 = time.perf_counter()
        r = run_scenario(spec_for(n, wan), "converge", heights=heights)
        wall = time.perf_counter() - t0
        assert r["converged_heights"] == heights, r
        accepted_ratio = r["duplicate_vote_ratio"]
        committed = max(r["final_heights"])
        rows.append({
            "mode": f"scale:n={n}" + (f":wan={wan}" if wan else ""),
            "nodes": n,
            "topology": r["topology"],
            "heights_per_s": round(r["heights_per_s"], 3),
            "duplicate_vote_ratio": round(accepted_ratio, 4),
            "gossip_bytes_per_height": round(r["gossip_bytes"] / committed)
            if committed else None,
            "converged_heights": r["converged_heights"],
            "wall_s": round(wall, 1),
        })
        if n == 10:
            ratio_at_10 = accepted_ratio

    if not SMOKE:
        # -- dedup on-vs-off A/B at n=10 ------------------------------------
        r = run_scenario(spec_for(10, "", dedup=False), "converge", heights=5)
        off_ratio = r["duplicate_vote_ratio"]
        assert ratio_at_10 is not None
        assert ratio_at_10 < off_ratio, (
            f"has-vote dedup did not reduce duplicate votes: "
            f"on={ratio_at_10:.4f} vs off={off_ratio:.4f}"
        )
        rows.append({
            "mode": "dedup_ab:n=10",
            "ratio_dedup_on": round(ratio_at_10, 4),
            "ratio_dedup_off": round(off_ratio, 4),
            "reduction": round(1 - ratio_at_10 / off_ratio, 3)
            if off_ratio else None,
        })

        # -- the same A/B on a RING at n=10 (round 21): 10 nodes would
        # auto-mesh full, but the hundreds-of-nodes shape is sparse —
        # votes arrive mostly by RELAY, where the has-vote gate (not the
        # receiver's dup counter alone) earns its keep ------------------
        r = run_scenario(
            spec_for(10, "", topology="ring"), "converge", heights=5)
        ring_on = r["duplicate_vote_ratio"]
        r = run_scenario(
            spec_for(10, "", dedup=False, topology="ring"),
            "converge", heights=5)
        ring_off = r["duplicate_vote_ratio"]
        assert ring_on < ring_off, (
            f"has-vote dedup did not reduce duplicate votes on the ring: "
            f"on={ring_on:.4f} vs off={ring_off:.4f}"
        )
        rows.append({
            "mode": "dedup_ab:ring:n=10",
            "ratio_dedup_on": round(ring_on, 4),
            "ratio_dedup_off": round(ring_off, 4),
            "reduction": round(1 - ring_on / ring_off, 3)
            if ring_off else None,
        })

        # -- a netchaos fault at process scale ------------------------------
        t0 = time.perf_counter()
        r = run_scenario(spec_for(10, ""), "partition_heal", heights=2)
        rows.append({
            "mode": "partition_heal:n=10",
            "healed_to_height": r["heights"],
            "minority_frozen_at": r["minority_frozen_at"],
            "converged_heights": r["converged_heights"],
            "wall_s": round(time.perf_counter() - t0, 1),
        })

    record = {
        "bench": "localnet",
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": "cpu",
        "smoke": SMOKE,
        "cores": os.cpu_count(),
        "rows": rows,
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r20.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
