"""WAL durability bench (round 9): what group commit buys the consensus
commit hot path, and what repair costs recovery. Writes BENCH_r09.json.

Every consensus input used to pay a synchronous fsync before it was
handled (consensus/wal.go:73-95 semantics); round 9's v2 WAL batches the
fsync behind a bounded flush interval and only forces it on #ENDHEIGHT
(docs/crash-recovery.md). This bench measures the two modes on identical
record streams, plus the repair/recovery scan on a 10k-record WAL with a
deliberately torn+garbaged tail, and runs a mini torture sweep (truncate
at every byte offset of the final records, reopen, verify the clean
prefix) so `make wal-torture-smoke` gates the repair logic chip-free.

Rows:
- fsync_per_record: sync_every_write=True save() throughput + p50 latency
- group_commit:     default mode save() throughput, fsync count, group size
- recovery_scan:    WAL open (repair pass) + #ENDHEIGHT search on a
                    10k-record log whose tail is torn and garbaged
- torture_smoke:    byte-offset sweep over the tail records, all recovered

Asserted floor (gates `make wal-torture-smoke` in tier1): group commit
>= 1.3x fsync-per-record msgs/s (measured 10-100x on real disks — fsync
here costs ~3 ms) and every torture offset recovers. The ratio floor only
gates when fsync measurably costs something (p50 >= 100 us) — on a
filesystem where fsync is free (tmpfs checkout, eatmydata CI, fsync=off
VMs) both modes collapse to buffered-write speed and the ratio says
nothing about the code, so it is reported but not asserted; the repair
and torture rows assert unconditionally.

These numbers are chip-free BY CONSTRUCTION — the WAL is a host-plane
component; no device, daemon, or jax backend is involved, so no
live-chip re-record is ever owed (ROADMAP ledger).

BENCH_WAL_SMOKE=1 shrinks the record counts for the tier-1 gate.
Prints ONE JSON line like the other benches.
Run from the repo root: python benches/bench_wal.py
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_WAL_SMOKE", "") == "1"
N_SYNC = int(os.environ.get("BENCH_WAL_SYNC_RECORDS", "150" if SMOKE else "400"))
N_GROUP = int(os.environ.get("BENCH_WAL_GROUP_RECORDS", "4000" if SMOKE else "10000"))
N_SCAN = int(os.environ.get("BENCH_WAL_SCAN_RECORDS", "4000" if SMOKE else "10000"))
N_TORTURE_RECORDS = 3  # tail records swept byte-by-byte
MIN_RATIO = float(os.environ.get("BENCH_WAL_MIN_RATIO", "1.3"))
# below this measured fsync p50 the filesystem is effectively sync-free
# and the group-vs-per-record ratio is meaningless (see module docstring)
FSYNC_FLOOR_US = float(os.environ.get("BENCH_WAL_FSYNC_FLOOR_US", "100"))


def _fsync_p50_us(dirpath: str, n: int = 25) -> float:
    """Median latency of a 1-byte append + fsync on this filesystem."""
    probe = os.path.join(dirpath, "fsync-probe")
    lat = []
    with open(probe, "wb") as f:
        for _ in range(n):
            f.write(b"x")
            f.flush()
            t0 = time.perf_counter()
            os.fsync(f.fileno())
            lat.append(time.perf_counter() - t0)
    os.unlink(probe)
    return statistics.median(lat) * 1e6


def _record(i: int) -> dict:
    # a realistic consensus input record (timeout-shaped, ~120 B framed)
    return {
        "type": "timeout",
        "timeout": {"duration": 0.05, "height": i, "round": 0, "step": 3},
    }


def _run_writer(dirpath: str, n: int, sync_every: bool) -> dict:
    from tendermint_tpu.consensus.wal import WAL, WALMessage  # noqa: F401

    path = os.path.join(dirpath, "wal")
    w = WAL(path, sync_every_write=sync_every, flush_interval_s=0.05)
    w.start()
    lat = []
    t0 = time.perf_counter()
    for i in range(n):
        t1 = time.perf_counter()
        w.save(_record(i))
        lat.append(time.perf_counter() - t1)
    # one ENDHEIGHT close the way a commit would, so the group-commit row
    # includes its durability point
    w.write_end_height(1)
    elapsed = time.perf_counter() - t0
    stats = w.stats()
    w.stop()
    return {
        "records": n,
        "msgs_per_sec": round((n + 1) / elapsed, 1),
        "save_p50_us": round(statistics.median(lat) * 1e6, 1),
        "fsyncs": stats["fsyncs"],
        "group_size_avg": stats["group_size_avg"],
    }


def _build_big_wal(dirpath: str, n: int) -> str:
    from tendermint_tpu.consensus.wal import WAL, MAGIC  # noqa: F401

    path = os.path.join(dirpath, "wal")
    w = WAL(path, flush_interval_s=10.0)
    w.start()
    for i in range(n):
        w.save(_record(i))
        if i % 500 == 499:
            w.write_end_height(i // 500 + 1)
    w.stop()
    return path


def main() -> None:
    # bench on the repo filesystem: /tmp may be tmpfs-ish where fsync is
    # free and the per-record row would understate the real gap. A SIGTERM
    # (the Makefile's `timeout`) skips the finally, so sweep strays from
    # earlier runs first — they are gitignored but still clutter.
    for stale in glob.glob(os.path.join(ROOT, "bench-wal-*")):
        shutil.rmtree(stale, ignore_errors=True)
    workdir = tempfile.mkdtemp(prefix="bench-wal-", dir=ROOT)
    rows = []
    try:
        fsync_p50_us = round(_fsync_p50_us(workdir), 1)
        d1 = os.path.join(workdir, "sync")
        os.makedirs(d1)
        per_record = _run_writer(d1, N_SYNC, sync_every=True)
        rows.append({"mode": "fsync_per_record", **per_record})

        d2 = os.path.join(workdir, "group")
        os.makedirs(d2)
        group = _run_writer(d2, N_GROUP, sync_every=False)
        ratio = group["msgs_per_sec"] / per_record["msgs_per_sec"]
        rows.append({
            "mode": "group_commit",
            **group,
            "vs_fsync_per_record": round(ratio, 2),
        })

        # recovery scan: 10k records, tail torn mid-frame + garbage suffix
        d3 = os.path.join(workdir, "scan")
        os.makedirs(d3)
        path = _build_big_wal(d3, N_SCAN)
        with open(path, "r+b") as f:
            f.seek(0, 2)
            f.truncate(f.tell() - 7)  # torn final frame
        with open(path, "ab") as f:
            f.write(b"\x00" * 33 + b"\xf3garbage")
        from tendermint_tpu.consensus.wal import WAL

        t0 = time.perf_counter()
        w = WAL(path)
        repair_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        lines = w.lines_after_height(N_SCAN // 500 - 1)
        scan_s = time.perf_counter() - t0
        s = w.stats()
        w.group.close()
        assert s["repairs"] == 1 and lines, "scan WAL failed to repair"
        wal_bytes = sum(
            os.path.getsize(p) for p in glob.glob(path + "*")
        )
        rows.append({
            "mode": "recovery_scan",
            "records": N_SCAN,
            "wal_mb": round(wal_bytes / 1e6, 2),
            "repair_open_ms": round(repair_s * 1e3, 2),
            "endheight_search_ms": round(scan_s * 1e3, 2),
            "truncated_bytes": s["truncated_bytes"],
        })

        # torture smoke: every byte offset of the final records
        d4 = os.path.join(workdir, "torture")
        os.makedirs(d4)
        tpath = _build_big_wal(d4, 12)
        with open(tpath, "rb") as f:
            raw = f.read()
        from tendermint_tpu.consensus.wal import scan_frames

        payloads, bad = scan_frames(raw)
        assert bad is None
        tail_start = len(raw) - sum(
            8 + len(p) for p in payloads[-N_TORTURE_RECORDS:]
        )
        swept = 0
        for cut in range(tail_start, len(raw) + 1):
            case = os.path.join(d4, f"c{cut}", "wal")
            os.makedirs(os.path.dirname(case))
            with open(case, "wb") as f:
                f.write(raw[:cut])
            w = WAL(case)
            expect, _ = scan_frames(raw[:cut])
            got = w.read_all_lines()
            w.group.close()
            assert got == [b.decode() for b in expect], f"offset {cut}"
            swept += 1
        rows.append({
            "mode": "torture_smoke",
            "offsets_swept": swept,
            "all_recovered": True,
        })

        if fsync_p50_us >= FSYNC_FLOOR_US:
            assert ratio >= MIN_RATIO, (
                f"group commit {ratio:.2f}x fsync-per-record is under the "
                f"{MIN_RATIO}x floor (fsync p50 {fsync_p50_us} us)"
            )
        else:
            print(
                f"# fsync p50 {fsync_p50_us} us < {FSYNC_FLOOR_US} us floor: "
                "sync-free filesystem, ratio reported but not asserted",
                file=sys.stderr,
            )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": "WAL group commit vs fsync-per-record + repair/recovery scan",
        "min_ratio_asserted": MIN_RATIO,
        "fsync_p50_us": fsync_p50_us,
        "ratio_gated": fsync_p50_us >= FSYNC_FLOOR_US,
        "smoke": SMOKE,
        "rows": rows,
        "note": (
            "host-plane only: chip-free BY CONSTRUCTION (no device/daemon/"
            "jax involved), no live-chip re-record owed; repo-fs fsync "
            "~3 ms dominates the per-record row"
        ),
    }
    if not SMOKE:
        # bench_partset's convention: the tier-1 smoke gate asserts but
        # never writes — otherwise every `make tier1` would clobber the
        # recorded full-run artifact with reduced smoke numbers
        with open(os.path.join(ROOT, "BENCH_r09.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "wal_group_commit_vs_fsync_per_record",
        "value": rows[1]["vs_fsync_per_record"],
        "unit": "x",
        "group_msgs_per_sec": rows[1]["msgs_per_sec"],
        "fsync_msgs_per_sec": rows[0]["msgs_per_sec"],
        "repair_open_ms": rows[2]["repair_open_ms"],
        "torture_offsets": rows[3]["offsets_swept"],
        "platform": "host",
        "smoke": SMOKE,
    }))


if __name__ == "__main__":
    main()
