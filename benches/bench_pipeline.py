"""Pipelined execution plane bench (round 14): end-to-end committed-tx/s
at saturating mempool load on a REAL single-validator durable consensus
chain, SEED execution plane vs the round-14 plane. Writes BENCH_r14.json.

The workload is the repo's flagship signed app (BASELINE config 5's
shape) under a hot-keyed saturating stream. The three chain rows:

- serial            = the SEED plane: inline finalize (apply + snapshot
                      hook + events on the consensus thread) and the
                      per-tx DeliverTx ReqRes dispatch, under which the
                      signed app verifies each tx's Ed25519 signature
                      one at a time in pure python — exactly what every
                      block paid before this round.
- pipelined         = the round-14 plane: staged finalize (block save +
                      WAL marker sync, apply/hook/events deferred to the
                      ordered executor, join at propose), whole-block
                      grouped DeliverTx dispatch, and the block's
                      signatures verified in ONE gateway batch per block
                      (the numpy/device kernel).
- pipelined_sharded = plus the keyspace-sharded parallel kvstore fold
                      (app.shards = TENDERMINT_KVSTORE_SHARDS semantics).

Every run commits the SAME deterministic workload: a seeded validator
key, pinned genesis + block times (ConsensusState.propose_time_source),
and a fully preloaded mempool — so the bench ASSERTS the chains are
BYTE-IDENTICAL per height (block hash, part-set root, app hash, txs)
while their wall clocks differ: the new plane changes WHEN and HOW work
runs, never what is committed. pipelined >= 1.25x serial committed-tx/s
is asserted (measured ~17-34x across runs on this box: the per-tx
pure-python verify the seed plane paid is the dominating term the
batched plane removes); the smoke gate (`make pipeline-smoke`) asserts
the same identity with a reduced load.

A fourth row isolates the SCHEDULING win alone (round-14 plane with the
deferred apply toggled off vs on) and is recorded UNASSERTED: on this
2-core CPython box the GIL serializes the pure-python portions of the
overlap, so the deferral alone is worth only ~1.0-1.1x here (the
hook/events tail off the critical path); its real payoff is the receive
routine staying live for gossip during apply — a multi-node property the
netchaos tier exercises — and it is the structural prerequisite for the
big-committee and sharded-device-plane items (ROADMAP).

Chip-free: consensus + kvstore host planes; verify/hash ride the
gateway's CPU/AVX floor. A live-daemon row joins the standard tunnel
queue (the batched deliver verify routes through the same verify plane
BENCH_r06 records).

Run from the repo root: python benches/bench_pipeline.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SMOKE = os.environ.get("BENCH_PIPELINE_SMOKE", "") == "1"
N_HEIGHTS = int(os.environ.get("BENCH_PIPELINE_HEIGHTS", "3" if SMOKE else "6"))
TXS_PER_BLOCK = int(
    os.environ.get("BENCH_PIPELINE_TXS", "250" if SMOKE else "600")
)
VALUE_BYTES = int(os.environ.get("BENCH_PIPELINE_VALUE_BYTES", "96"))
TIMEOUT_COMMIT = float(
    os.environ.get("BENCH_PIPELINE_TIMEOUT_COMMIT", "0.03")
)
MIN_RATIO = float(
    os.environ.get("BENCH_PIPELINE_MIN_RATIO", "1.1" if SMOKE else "1.25")
)
SHARDS = int(os.environ.get("BENCH_PIPELINE_SHARDS", "2"))
KEY_SPACE = int(os.environ.get("BENCH_PIPELINE_KEY_SPACE", "300"))
GENESIS_NS = 1_700_000_000_000_000_000


_WORKLOAD_CACHE: list[bytes] = []


def _workload() -> list[bytes]:
    """Hot-keyed kv txs: a bounded working set hammered by a saturating
    stream (the exchange/hot-account shape). Keys cycle over KEY_SPACE so
    the app state — and the per-height snapshot cost — plateaus; tx
    bytes stay unique (the value carries i) so the mempool never dedupes
    them. Built once and reused by every run, so all chains commit the
    identical byte stream."""
    if not _WORKLOAD_CACHE:
        from tendermint_tpu.abci.apps.signedkv import make_sig_tx

        v = "x" * VALUE_BYTES
        for i in range(N_HEIGHTS * TXS_PER_BLOCK):
            seed = b"bench-signer-%08d" % i
            seed = seed + b"\x00" * (32 - len(seed))
            _WORKLOAD_CACHE.append(
                make_sig_tx(seed, f"k{i % KEY_SPACE:05d}={v}{i:06d}".encode())
            )
    return list(_WORKLOAD_CACHE)


def _build_cs(pipeline: bool, shards: int):
    """Deterministic single-validator ConsensusState over FileDB (the
    tests/consensus_common.py shape, inlined: benches run standalone)."""
    import tempfile

    from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.config import test_config
    from tendermint_tpu.consensus.state import ConsensusState
    from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
    from tendermint_tpu.libs.db import FileDB
    from tendermint_tpu.libs.events import EventSwitch
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.proxy.app_conn import AppConnConsensus, AppConnMempool
    from tendermint_tpu.state.state import State
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidatorFS

    pv = PrivValidatorFS(gen_priv_key_ed25519(b"bench-pipeline"), None)
    doc = GenesisDoc(
        genesis_time_ns=GENESIS_NS,
        chain_id="bench_pipeline",
        validators=[GenesisValidator(pv.get_pub_key(), 1, "v0")],
    )
    home = tempfile.mkdtemp(prefix="bench-pipeline-home-")
    # DURABLE node shape (the number that matters in production): state
    # DB + block store on FileDB, real fsyncs. This is also where the
    # pipeline's overlap is GIL-robust — the executor's state/app/
    # snapshot writes release the GIL against the consensus thread's
    # part-hashing, WAL group commit, and block-store writes
    state = State.get_state(FileDB(os.path.join(home, "state.db")), doc)
    # the repo's flagship signed app (BASELINE config 5's shape).
    # verify_in_app=False plays the production SigBatcher gate's role for
    # the direct mempool preload; the DELIVER path always verifies —
    # per tx (pure python) on the seed plane, one gateway batch per
    # block on the round-14 plane
    app = SignedKVStoreApp(verify_in_app=False)
    app.shards = shards
    app.shard_min_txs = 16
    mtx = threading.RLock()
    mp_cfg = test_config().mempool
    # saturating-load policy: the preloaded pool would otherwise re-run
    # CheckTx over every remaining tx INSIDE each apply (mempool.update
    # recheck) — an O(pool) cost both modes pay identically that only
    # drowns the signal; production load-tuned nodes disable it too
    mp_cfg.recheck = False
    mp = Mempool(mp_cfg, AppConnMempool(LocalClient(app, mtx)))
    cfg = test_config().consensus
    cfg.root_dir = tempfile.mkdtemp(prefix="bench-pipeline-")
    cfg.timeout_commit = TIMEOUT_COMMIT
    cfg.skip_timeout_commit = False  # the commit window IS the overlap
    cfg.max_block_size_txs = TXS_PER_BLOCK
    # byte-identity across runs requires every height to commit at round
    # 0: a step timeout firing under load in ONE run would bump the vote
    # round, changing the next block's last_commit bytes. A single
    # validator never needs the liveness timeouts — make them generous.
    cfg.timeout_propose = 30.0
    cfg.timeout_prevote = 30.0
    cfg.timeout_precommit = 30.0
    evsw = EventSwitch()
    evsw.start()
    store = BlockStore(FileDB(os.path.join(home, "blockstore.db")))
    cs = ConsensusState(
        cfg, state, AppConnConsensus(LocalClient(app, mtx)), store, mp,
    )
    cs.set_event_switch(evsw)
    cs.set_priv_validator(pv)
    cs.pipeline_apply = pipeline
    cs.propose_time_source = lambda h: GENESIS_NS + h * 1_000_000_000
    # the production post-apply hook: a statesync snapshot producer at
    # interval=1 (a statesync-serving node under load). Serial pays it
    # inline per height; the pipeline runs it as the executor's tail,
    # off the critical path (docs/execution-pipeline.md)
    from tendermint_tpu.statesync import SnapshotProducer, SnapshotStore

    producer = SnapshotProducer(
        SnapshotStore(tempfile.mkdtemp(prefix="bench-pipeline-snap-")),
        app, store, interval=1, keep_recent=2, full_every=1,
    )
    cs.post_apply_hook = producer.maybe_snapshot
    return cs, app


def _run(label: str, pipeline: bool, shards: int,
         legacy_dispatch: bool = False) -> dict:
    # legacy_dispatch restores the pre-round-14 execution plane (per-tx
    # DeliverTx ReqRes dispatch) for the serial baseline row
    if legacy_dispatch:
        os.environ["TENDERMINT_DELIVER_BATCH"] = "0"
    else:
        os.environ.pop("TENDERMINT_DELIVER_BATCH", None)
    cs, app = _build_cs(pipeline, shards)
    txs = _workload()
    for tx in txs:
        cs.mempool.check_tx(tx)
    done = threading.Event()

    from tendermint_tpu.types import events as tev

    committed = []

    def on_block(data):
        committed.append(data.block.header.height)
        if len(committed) >= N_HEIGHTS:
            done.set()

    cs.evsw.add_listener_for_event("bench", tev.EVENT_NEW_BLOCK, on_block)
    t0 = time.perf_counter()
    cs.start()
    ok = done.wait(timeout=60 + N_HEIGHTS * 10)
    wall_s = time.perf_counter() - t0
    cs.stop()
    if not ok:
        raise SystemExit(f"{label}: chain stalled at height {cs.rs.height}")
    fps = {}
    n_txs = 0
    for h in range(1, N_HEIGHTS + 1):
        meta = cs.block_store.load_block_meta(h)
        block = cs.block_store.load_block(h)
        n_txs += len(block.data.txs)
        fps[h] = (
            meta.block_id.hash.hex(),
            meta.block_id.parts_header.hash.hex(),
            block.header.app_hash.hex(),
            tuple(tx.hex() for tx in block.data.txs),
        )
    row = {
        "row": label,
        "pipeline": pipeline,
        "shards": shards,
        "heights": N_HEIGHTS,
        "committed_txs": n_txs,
        "wall_s": round(wall_s, 4),
        "committed_tx_per_sec": round(n_txs / wall_s, 1),
        "pipeline_applies": cs.pipeline_applies,
        "join_wait_last_s": round(cs.pipeline_join_wait_last, 5),
        "overlap_last_s": round(cs.pipeline_overlap_last, 5),
        "sharded_batches": getattr(app, "sharded_batches", 0),
        "platform": "host",
    }
    return row, fps


def _sharded_apply_row() -> dict:
    """App-level row: the sharded fold + deterministic merge vs the
    serial per-tx loop on one wide block, roots asserted identical."""
    from tendermint_tpu.abci.apps.kvstore import KVStoreApp

    n = 2000 if SMOKE else 8000
    v = "y" * VALUE_BYTES
    txs = [f"shard{i % (n // 3):05d}={v}{i}".encode() for i in range(n)]
    serial, sharded = KVStoreApp(), KVStoreApp()
    sharded.shards = SHARDS
    sharded.shard_min_txs = 16

    t0 = time.perf_counter()
    for tx in txs:
        serial.deliver_tx(tx)
    root_serial = serial.commit().data
    serial_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    sharded.deliver_txs(list(txs))
    root_sharded = sharded.commit().data
    sharded_s = time.perf_counter() - t0

    assert root_serial == root_sharded, (
        "sharded apply forked the VersionedTree root"
    )
    assert sharded.sharded_batches == 1
    return {
        "row": "sharded_apply_block",
        "txs": n,
        "shards": SHARDS,
        "serial_s": round(serial_s, 4),
        "sharded_s": round(sharded_s, 4),
        "vs_serial": round(serial_s / sharded_s, 3) if sharded_s else 0.0,
        "roots_identical": True,
        "note": "hot-keyed fold: one tree/dict mutation per FINAL key "
                "instead of per tx, priorities in one batched RIPEMD pass "
                "(~4x at this 3:1 tx:key shape); vs_serial unasserted — "
                "shape-dependent, the asserted property is root "
                "byte-identity",
        "platform": "host",
    }


def main() -> None:
    rows = []
    # serial baseline = the SEED execution plane: inline finalize + the
    # per-tx DeliverTx ReqRes dispatch (what every height paid before
    # round 14)
    serial_row, serial_fps = _run(
        "serial", pipeline=False, shards=0, legacy_dispatch=True
    )
    rows.append(serial_row)
    piped_row, piped_fps = _run("pipelined", pipeline=True, shards=0)
    rows.append(piped_row)
    shard_row, shard_fps = _run(
        "pipelined_sharded", pipeline=True, shards=SHARDS
    )
    rows.append(shard_row)

    # the acceptance bar: identical chains, faster clock
    assert piped_fps == serial_fps, "pipelined chain diverged from serial"
    assert shard_fps == serial_fps, "sharded chain diverged from serial"
    assert piped_row["pipeline_applies"] >= N_HEIGHTS
    assert shard_row["sharded_batches"] >= 1, (
        "wide blocks never took the sharded apply path"
    )
    ratio = (
        piped_row["committed_tx_per_sec"] / serial_row["committed_tx_per_sec"]
    )
    rows.append({
        "row": "pipelined_vs_serial",
        "ratio": round(ratio, 3),
        "min_asserted": MIN_RATIO,
        "byte_identity": "block hash + part-set root + app hash + txs, "
                         "all heights, all runs",
    })
    assert ratio >= MIN_RATIO, (
        f"pipelined committed-tx/s only {ratio:.2f}x serial "
        f"(floor {MIN_RATIO}x)"
    )

    # isolate the SCHEDULING win: the round-14 deliver plane (grouped
    # dispatch + batched verify) with the deferred apply OFF — the delta
    # against piped_row is what the pipeline alone buys. Unasserted by
    # design: see the module docstring's GIL note.
    batched_serial_row, batched_serial_fps = _run(
        "serial_batched_deliver", pipeline=False, shards=0
    )
    assert batched_serial_fps == serial_fps, (
        "batched-deliver serial chain diverged"
    )
    sched_ratio = (
        piped_row["committed_tx_per_sec"]
        / batched_serial_row["committed_tx_per_sec"]
    )
    batched_serial_row["pipeline_only_ratio"] = round(sched_ratio, 3)
    batched_serial_row["note"] = (
        "deferred-apply scheduling alone (both sides on the batched "
        "deliver plane); GIL-bound on this box — unasserted"
    )
    rows.append(batched_serial_row)
    rows.append(_sharded_apply_row())

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": "pipelined execution plane: committed-tx/s at saturating "
                  "mempool load, serial vs pipelined vs pipelined+sharded",
        "heights": N_HEIGHTS,
        "txs_per_block": TXS_PER_BLOCK,
        "timeout_commit_s": TIMEOUT_COMMIT,
        "min_ratio_asserted": MIN_RATIO,
        "smoke": SMOKE,
        "rows": rows,
        "note": "chip-free (consensus/kvstore host planes; scheduling "
                "change, no device kernel — no live-chip row owed)",
    }
    if not SMOKE:
        with open(os.path.join(ROOT, "BENCH_r14.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    print(json.dumps({
        "metric": "pipeline_committed_tx_per_sec",
        "serial": serial_row["committed_tx_per_sec"],
        "pipelined": piped_row["committed_tx_per_sec"],
        "pipelined_sharded": shard_row["committed_tx_per_sec"],
        "vs_serial": round(ratio, 3),
        "unit": "tx/s",
        "platform": "host",
        "smoke": SMOKE,
    }))


if __name__ == "__main__":
    main()
