"""BASELINE config 3: PartSet Merkle-root + SimpleProof verify.

1 MB block split into 64 KB parts (the reference's defaults,
types/part_set.go:95-122 + config defaults in BASELINE.md): per-block
part-set construction — RIPEMD-160 per part + Merkle tree + per-part
proofs — through the production TPU hashing gateway vs the pure-CPU
path, with byte-identical headers asserted and every proof verified.

Round 7 adds the hash-plane rows (writes BENCH_r07.json, every row with
its platform):

- host-builder row (ALWAYS, asserted >= BENCH_HOST_BUILDER_MIN, default
  1.5x): the flat level-order builder + shared-aunt proofs
  (merkle.simple.FlatTree) vs the recursive reference
  (recursive_proofs_from_hashes) at the production 16-leaf shape.
- sim-transport row (ALWAYS, asserted >= BENCH_HASH_STREAM_MIN, default
  1.3x): a sim-device daemon (devd._SimHasher — FIFO real-digest hashing
  at a fixed rate) holds device time constant, so single-shot vs
  streamed hash offload isolates the IPC transport, exactly like the
  PR-1 verify bench (bench_devd_stream.py).
- live row (only when a daemon already serves, e.g. a TPU box): the same
  streamed-vs-single-shot comparison against the held accelerator at the
  real 1 MB / 64 KB part shape — the row the next tunnel window fills in
  (ROADMAP: the 3_partset standing record predates the stream).

BENCH_PARTSET_SMOKE=1 runs ONLY the two chip-free asserted rows (the
`make hash-stream-smoke` tier-1 gate) and skips the jax offload
measurement.

Prints ONE JSON line like bench.py.
Run from the repo root: python benches/bench_partset.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BLOCK_MB = int(os.environ.get("BENCH_BLOCK_MB", "1"))
PART_SIZE = int(os.environ.get("BENCH_PART_SIZE", str(64 * 1024)))
N_BLOCKS = int(os.environ.get("BENCH_N_BLOCKS", "24"))
SMOKE = os.environ.get("BENCH_PARTSET_SMOKE", "") == "1"

# sim-transport row shape: 16 MB of 1 KB leaves — wide enough that the
# single-shot path's pickle-the-world marshal dominates its round trip
# (measured ~2.5x here; asserted floor leaves margin for loaded boxes)
HS_ITEMS = int(os.environ.get("BENCH_HASH_STREAM_ITEMS", "16384"))
HS_ITEM_BYTES = int(os.environ.get("BENCH_HASH_STREAM_ITEM_BYTES", "1024"))
HS_CHUNK = int(os.environ.get("BENCH_HASH_STREAM_CHUNK", "1024"))
HS_TRIALS = int(os.environ.get("BENCH_HASH_STREAM_TRIALS", "3" if SMOKE else "5"))
HS_SIM_RATE = float(os.environ.get("BENCH_HASH_STREAM_SIM_RATE", "1000000"))
HS_MIN_SPEEDUP = float(os.environ.get("BENCH_HASH_STREAM_MIN", "1.3"))
HB_MIN_SPEEDUP = float(os.environ.get("BENCH_HOST_BUILDER_MIN", "1.5"))


def _platform_label() -> str:
    from tendermint_tpu.jitcache import platform_label

    return platform_label()


# -- host-builder row: flat vs recursive proofs build -------------------------


def bench_host_builder() -> dict:
    """Flat (FlatTree + shared-aunt views) vs recursive proofs build at
    the 1 MB / 64 KB shape — leaf hashing excluded on both sides, so the
    row isolates exactly the builder the tentpole replaced."""
    from tendermint_tpu.crypto.hashing import ripemd160
    from tendermint_tpu.merkle.simple import (
        recursive_proofs_from_hashes,
        simple_proofs_from_hashes,
    )

    n_parts = max((BLOCK_MB << 20) // PART_SIZE, 1)
    digests = [ripemd160(b"part-%d" % i) for i in range(n_parts)]
    iters = 300 if SMOKE else 2000
    for _ in range(50):  # warm the shape cache + allocator
        simple_proofs_from_hashes(digests)
        recursive_proofs_from_hashes(digests)

    flat_s = rec_s = float("inf")
    for _ in range(5):  # best-of-5, alternated
        t0 = time.perf_counter()
        for _ in range(iters):
            simple_proofs_from_hashes(digests)
        flat_s = min(flat_s, time.perf_counter() - t0)
        t0 = time.perf_counter()
        for _ in range(iters):
            recursive_proofs_from_hashes(digests)
        rec_s = min(rec_s, time.perf_counter() - t0)
    # materialized variant: every proof's aunts forced (the gossip
    # serialize cost) — reported for honesty, not asserted
    t0 = time.perf_counter()
    for _ in range(iters):
        _, proofs = simple_proofs_from_hashes(digests)
        for p in proofs:
            p.aunts
    flat_mat_s = time.perf_counter() - t0

    root_ref, proofs_ref = recursive_proofs_from_hashes(digests)
    root_flat, proofs_flat = simple_proofs_from_hashes(digests)
    assert root_flat == root_ref, "flat builder root diverges"
    for i in range(n_parts):
        assert proofs_flat[i].aunts == proofs_ref[i].aunts, f"proof {i}"
        assert proofs_flat[i].verify(i, n_parts, digests[i], root_ref)

    return {
        "mode": "host-builder",
        "platform": "cpu",
        "leaves": n_parts,
        "builds": iters,
        "flat_us_per_build": round(flat_s / iters * 1e6, 2),
        "recursive_us_per_build": round(rec_s / iters * 1e6, 2),
        "flat_materialized_us_per_build": round(flat_mat_s / iters * 1e6, 2),
        "speedup": round(rec_s / flat_s, 3),
        "speedup_materialized": round(rec_s / flat_mat_s, 3),
        "parity": "roots+proofs byte-identical",
    }


# -- sim-transport row: streamed vs single-shot hash offload ------------------


def _spawn_daemon(extra_env: dict) -> tuple[subprocess.Popen, str, str]:
    run_dir = tempfile.mkdtemp(prefix="bench-hashd-")
    sock = os.path.join(run_dir, "devd.sock")
    env = {
        **os.environ,
        "TENDERMINT_DEVD_SOCK": sock,
        "TENDERMINT_DEVD_ACCEPT_CPU": "1",
        "TENDERMINT_DEVD_EXIT_ON_TERM": "1",
        **extra_env,
    }
    # stderr to a FILE, not a pipe: nothing drains a pipe while the
    # bench measures, so a chatty daemon (jax warnings + a few
    # tracebacks) would fill the ~64 KB pipe buffer, block on write,
    # and hang the tier-1 smoke gate with no timeout
    err_path = os.path.join(run_dir, "daemon.err")
    with open(err_path, "wb") as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.devd"],
            env=env, cwd=ROOT,
            stdout=subprocess.DEVNULL, stderr=err_f,
        )
    return proc, sock, err_path


def _wait_held(client, proc, err_path: str, deadline_s: float) -> None:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            try:
                with open(err_path, "rb") as f:
                    err = f.read()
            except OSError:
                err = b""
            raise RuntimeError(f"daemon died: {err[-2000:]!r}")
        try:
            if client.ping(timeout=2.0).get("held"):
                return
        except Exception:  # noqa: BLE001 — still starting
            pass
        time.sleep(0.5)
    raise RuntimeError("daemon never reached serving state")


def _measure_hash_transport(client, items, chunk: int, trials: int) -> dict:
    """Best-of-`trials` each way, alternated. Single-shot = the pre-r7
    offload path: the WHOLE leaf batch as one pickled request, one
    monolithic round trip."""
    n = len(items)
    client.hash_batch(items[: min(n, 256)])  # connection + import warm
    client.hash_stream(items[: min(n, 256)], chunk=max(chunk // 8, 32))
    single_best = stream_best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        r1 = client.hash_batch(items)
        single_best = min(single_best, time.perf_counter() - t0)
        t0 = time.perf_counter()
        r2 = client.hash_stream(items, chunk=chunk)
        stream_best = min(stream_best, time.perf_counter() - t0)
        assert r1 == r2, "streamed digests diverge from single-shot"
    mb = sum(len(it) for it in items) / 1e6
    return {
        "items": n,
        "item_bytes": len(items[0]),
        "chunk": chunk,
        "single_shot_mb_per_sec": round(mb / single_best, 2),
        "streamed_mb_per_sec": round(mb / stream_best, 2),
        "speedup": round(single_best / stream_best, 3),
        "single_shot_ms": round(single_best * 1000, 1),
        "streamed_ms": round(stream_best * 1000, 1),
    }


def bench_sim_transport() -> dict:
    from tendermint_tpu import devd

    proc, sock, err_path = _spawn_daemon(
        {"TENDERMINT_DEVD_SIM_RATE": str(int(HS_SIM_RATE))}
    )
    try:
        client = devd.DevdClient(sock)
        _wait_held(client, proc, err_path, 60.0)
        items = [
            bytes([i % 251]) * HS_ITEM_BYTES for i in range(HS_ITEMS)
        ]
        row = _measure_hash_transport(client, items, HS_CHUNK, HS_TRIALS)
        row.update(
            mode="sim-transport", platform="sim",
            sim_device_items_per_sec=HS_SIM_RATE,
        )
        row["daemon_hash_stream"] = client.status().get("hash_stream", {})
        client.shutdown()
        client.close()
    finally:
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
    return row


def bench_live_daemon() -> dict | None:
    """Streamed vs single-shot hash offload against an ALREADY-serving
    daemon (the live-chip window), at the real part shape."""
    from tendermint_tpu import devd

    live = devd.available(timeout=3.0)
    if live is None:
        return None
    client = devd.DevdClient()
    blocks = _blocks()
    parts = [
        blocks[i % 4][j * PART_SIZE: (j + 1) * PART_SIZE]
        for i in range(N_BLOCKS)
        for j in range((BLOCK_MB << 20) // PART_SIZE)
    ]
    row = _measure_hash_transport(client, parts, 8, max(2, HS_TRIALS - 2))
    row.update(platform=live.get("platform"), mode="live-daemon")
    row["daemon_hash_stream"] = client.status().get("hash_stream", {})
    client.close()
    return row


def _blocks() -> list[bytes]:
    return [
        bytes([(i * 37 + j) & 0xFF for j in range(256)]) * (BLOCK_MB * 4096)
        for i in range(4)
    ]


# -- the original gateway row (full mode only) --------------------------------


def bench_gateway() -> dict:
    from tendermint_tpu.ops import gateway as _gw
    from tendermint_tpu.ops.gateway import Hasher
    from tendermint_tpu.types.part_set import PartSet

    blocks = _blocks()
    # production hasher: transport-keyed default (offload iff the
    # measured device rtt is local-chip scale — gateway.Hasher/
    # device_rtt_ms), TPU offload kernels measured separately below
    prod = Hasher()
    rtt = _gw.device_rtt_ms()
    # offload measurement dials the device directly; honor an explicit
    # disable (run_all pins it when the tunnel is unreachable) and stand
    # down when a device daemon holds the chip — the in-process dial
    # would contend with the daemon's exclusive session (with a daemon
    # serving, the offload path is the live row's streamed IPC instead)
    from tendermint_tpu import devd

    offload = (
        os.environ.get("TENDERMINT_TPU_DISABLE", "") != "1"
        and devd.available() is None
    )
    tpu = Hasher(min_tpu_batch=1, use_tpu=offload)

    # warmup / compile the offload kernel
    warm = PartSet.from_data(blocks[0], PART_SIZE, hasher=tpu.part_leaf_hashes)

    # -- plain CPU reference vs production gateway path --------------------
    # best-of-3, alternating order, so run-order noise can't put the
    # production wrapper artificially above/below the plain path
    cpu_s = prod_s = float("inf")
    cpu_sets = prod_sets = None
    for _ in range(3):
        t0 = time.perf_counter()
        sets = [
            PartSet.from_data(blocks[i % 4], PART_SIZE) for i in range(N_BLOCKS)
        ]
        if (dt := time.perf_counter() - t0) < cpu_s:
            cpu_s, cpu_sets = dt, sets

        t0 = time.perf_counter()
        sets = [
            PartSet.from_data(
                blocks[i % 4], PART_SIZE, hasher=prod.part_leaf_hashes
            )
            for i in range(N_BLOCKS)
        ]
        if (dt := time.perf_counter() - t0) < prod_s:
            prod_s, prod_sets = dt, sets

    # -- TPU offload kernel (per-block calls: the production shape) -------
    t0 = time.perf_counter()
    tpu_sets = [
        PartSet.from_data(blocks[i % 4], PART_SIZE, hasher=tpu.part_leaf_hashes)
        for i in range(N_BLOCKS)
    ]
    tpu_s = time.perf_counter() - t0

    # -- parity + proof verification --------------------------------------
    assert warm.header() == cpu_sets[0].header()
    for c, p, t in zip(cpu_sets, prod_sets, tpu_sets):
        assert c.header() == t.header() == p.header(), "part-set header mismatch"
    ps = tpu_sets[0]
    root = ps.header().hash
    for i in range(ps.total):
        part = ps.get_part(i)
        assert part.proof.verify(i, ps.total, part.hash(), root), f"proof {i}"

    mb = BLOCK_MB * N_BLOCKS
    return {
        "metric": "partset_merkle_mb_per_sec",
        "value": round(mb / prod_s, 2),
        "unit": "MB/s",
        "vs_baseline": round(cpu_s / prod_s, 2),
        "detail": {
            "block_mb": BLOCK_MB,
            "part_kb": PART_SIZE // 1024,
            "n_blocks": N_BLOCKS,
            "cpu_mb_per_sec": round(mb / cpu_s, 2),
            "tpu_offload_mb_per_sec": round(mb / tpu_s, 2),
            **(
                {}
                if offload
                else {"offload": "stood down (no device, or a "
                      "daemon holds it) — tpu_offload number is "
                      "the CPU path"}
            ),
            "policy": (
                "transport-keyed (round 5): offload iff measured device "
                "rtt <= %.0f ms (or TENDERMINT_TPU_HASHES=1); round 7 "
                "adds the route — offload that IS on rides the streamed "
                "daemon IPC when a daemon serves, in-process otherwise — "
                "see gateway.Hasher; this box's rtt: %s"
                % (
                    _gw.HASH_RTT_MS_MAX,
                    ("%.1f ms" % rtt) if rtt is not None else
                    "n/a (no device / daemon holds it)",
                )
            ),
            "policy_model": {
                # VERDICT r3 asked for the tunnel confound to be
                # stated next to the number; VERDICT r4 ruled the
                # resulting "CPU-default FINAL" premature because
                # it generalized tunnel-biased data. The model:
                # through the axon tunnel (sync round-trip
                # 85-150 ms, H2D ~1.1 GB/s) a 1 MB/16-part
                # offload call pays >=85 ms RTT, capping ANY
                # tunneled hash kernel at ~8-11 MB/s — the
                # tunnel, not the kernel, sets that number
                # (measured r3: offload 2.28 vs CPU 205 MB/s).
                # Round 7's chunked hash_stream overlaps marshal,
                # IPC, and device compute (sim row: ~1.9-2.5x the
                # single-shot offload) — it narrows, but cannot
                # close, the tunneled gap; the live row above
                # measures by how much whenever a chip serves.
                # On a locally attached chip the RTT cap vanishes
                # and the question becomes compression-chain
                # serialism (a 64 KB part = 1024 strictly
                # sequential SHA/RIPEMD rounds, parallel only
                # across parts, no MXU help) vs the host AVX-512
                # path (~1.2 GB/s ripemd160_x16) — an empirical
                # question this bench answers wherever it runs
                # with a local chip; no such environment has been
                # available yet.
                "tunnel_rtt_s": [0.085, 0.150],
                "tunnel_h2d_gb_s": 1.1,
                "tunneled_cap_mb_s": [8, 11],
                "cpu_openssl_mb_s_per_core": 200,
            },
            "platform": _platform_label(),
            "offload_stats": tpu.stats(),
            "parity": "ok",
            "proofs": "verified",
        },
    }


def main() -> None:
    from tendermint_tpu.jitcache import enable as _enable_jit_cache

    _enable_jit_cache()

    rows = []
    live = None if SMOKE else bench_live_daemon()
    if live is not None:
        rows.append(live)
    host = bench_host_builder()
    rows.append(host)
    sim = bench_sim_transport()
    rows.append(sim)
    gateway_row = None if SMOKE else bench_gateway()

    record = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metric": "hash plane: streamed offload + flat host builder",
        "min_speedups_asserted": {
            "sim_transport_streamed": HS_MIN_SPEEDUP,
            "host_builder_flat": HB_MIN_SPEEDUP,
        },
        "rows": rows,
        "note": (
            "sim row isolates the hash IPC transport (device time "
            "constant); host row isolates the proofs builder; rows carry "
            "their platform so a live-chip window appends the TPU row "
            "against the same protocol (ROADMAP: 3_partset standing "
            "record predates the stream)"
        ),
    }
    if gateway_row is not None:
        record["gateway_row"] = gateway_row

    # assert BEFORE writing: a below-floor run must fail loudly without
    # clobbering the standing record with rows the bench itself rejected
    assert sim["speedup"] >= HS_MIN_SPEEDUP, (
        f"streamed hash offload only {sim['speedup']}x the single-shot "
        f"path (need >= {HS_MIN_SPEEDUP}x): {sim}"
    )
    assert host["speedup"] >= HB_MIN_SPEEDUP, (
        f"flat host builder only {host['speedup']}x the recursive one "
        f"(need >= {HB_MIN_SPEEDUP}x): {host}"
    )

    if not SMOKE:
        # the smoke gate (tier-1) asserts but never writes — only full
        # runs update BENCH_r07.json
        with open(os.path.join(ROOT, "BENCH_r07.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")

    if gateway_row is not None:
        out = dict(gateway_row)
        out["detail"] = dict(out["detail"])
        out["detail"]["hash_stream_rows"] = rows
        print(json.dumps(out))
    else:
        print(json.dumps({
            "metric": "hash_stream_streamed_mb_per_sec",
            "value": sim["streamed_mb_per_sec"],
            "unit": "MB/s",
            "vs_baseline": sim["speedup"],  # vs single-shot hash offload
            "detail": {"rows": rows, "platform": "sim"},
        }))


if __name__ == "__main__":
    sys.exit(main())
