"""BASELINE config 3: PartSet Merkle-root + SimpleProof verify.

1 MB block split into 64 KB parts (the reference's defaults,
types/part_set.go:95-122 + config defaults in BASELINE.md): per-block
part-set construction — RIPEMD-160 per part + Merkle tree + per-part
proofs — through the production TPU hashing gateway vs the pure-CPU
path, with byte-identical headers asserted and every proof verified.

Prints ONE JSON line like bench.py.
Run from the repo root: python benches/bench_partset.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.jitcache import enable as _enable_jit_cache
from tendermint_tpu.jitcache import platform_label

_enable_jit_cache()

BLOCK_MB = int(os.environ.get("BENCH_BLOCK_MB", "1"))
PART_SIZE = int(os.environ.get("BENCH_PART_SIZE", str(64 * 1024)))
N_BLOCKS = int(os.environ.get("BENCH_N_BLOCKS", "24"))


def main() -> None:
    from tendermint_tpu.ops.gateway import Hasher
    from tendermint_tpu.types.part_set import PartSet

    blocks = [
        bytes([(i * 37 + j) & 0xFF for j in range(256)]) * (BLOCK_MB * 4096)
        for i in range(4)
    ]  # 4 distinct 1MB payloads, cycled
    # production hasher: transport-keyed default (offload iff the
    # measured device rtt is local-chip scale — gateway.Hasher/
    # device_rtt_ms), TPU offload kernels measured separately below
    from tendermint_tpu.ops import gateway as _gw

    prod = Hasher()
    rtt = _gw.device_rtt_ms()
    # offload measurement dials the device directly; honor an explicit
    # disable (run_all pins it when the tunnel is unreachable) and stand
    # down when a device daemon holds the chip — hashing has no daemon
    # backend, and an in-process dial would contend with the daemon's
    # exclusive session
    from tendermint_tpu import devd

    offload = (
        os.environ.get("TENDERMINT_TPU_DISABLE", "") != "1"
        and devd.available() is None
    )
    tpu = Hasher(min_tpu_batch=1, use_tpu=offload)

    # warmup / compile the offload kernel
    warm = PartSet.from_data(blocks[0], PART_SIZE, hasher=tpu.part_leaf_hashes)

    # -- plain CPU reference vs production gateway path --------------------
    # best-of-3, alternating order, so run-order noise can't put the
    # production wrapper artificially above/below the plain path
    cpu_s = prod_s = float("inf")
    cpu_sets = prod_sets = None
    for _ in range(3):
        t0 = time.perf_counter()
        sets = [
            PartSet.from_data(blocks[i % 4], PART_SIZE) for i in range(N_BLOCKS)
        ]
        if (dt := time.perf_counter() - t0) < cpu_s:
            cpu_s, cpu_sets = dt, sets

        t0 = time.perf_counter()
        sets = [
            PartSet.from_data(
                blocks[i % 4], PART_SIZE, hasher=prod.part_leaf_hashes
            )
            for i in range(N_BLOCKS)
        ]
        if (dt := time.perf_counter() - t0) < prod_s:
            prod_s, prod_sets = dt, sets

    # -- TPU offload kernel (per-block calls: the production shape) -------
    t0 = time.perf_counter()
    tpu_sets = [
        PartSet.from_data(blocks[i % 4], PART_SIZE, hasher=tpu.part_leaf_hashes)
        for i in range(N_BLOCKS)
    ]
    tpu_s = time.perf_counter() - t0

    # -- parity + proof verification --------------------------------------
    assert warm.header() == cpu_sets[0].header()
    for c, p, t in zip(cpu_sets, prod_sets, tpu_sets):
        assert c.header() == t.header() == p.header(), "part-set header mismatch"
    ps = tpu_sets[0]
    root = ps.header().hash
    for i in range(ps.total):
        part = ps.get_part(i)
        assert part.proof.verify(i, ps.total, part.hash(), root), f"proof {i}"

    mb = BLOCK_MB * N_BLOCKS
    print(
        json.dumps(
            {
                "metric": "partset_merkle_mb_per_sec",
                "value": round(mb / prod_s, 2),
                "unit": "MB/s",
                "vs_baseline": round(cpu_s / prod_s, 2),
                "detail": {
                    "block_mb": BLOCK_MB,
                    "part_kb": PART_SIZE // 1024,
                    "n_blocks": N_BLOCKS,
                    "cpu_mb_per_sec": round(mb / cpu_s, 2),
                    "tpu_offload_mb_per_sec": round(mb / tpu_s, 2),
                    **(
                        {}
                        if offload
                        else {"offload": "stood down (no device, or a "
                              "daemon holds it) — tpu_offload number is "
                              "the CPU path"}
                    ),
                    "policy": (
                        "transport-keyed (round 5): offload iff measured "
                        "device rtt <= %.0f ms — see gateway.Hasher; "
                        "this box's rtt: %s"
                        % (
                            _gw.HASH_RTT_MS_MAX,
                            ("%.1f ms" % rtt) if rtt is not None else
                            "n/a (no device / daemon holds it)",
                        )
                    ),
                    "policy_model": {
                        # VERDICT r3 asked for the tunnel confound to be
                        # stated next to the number; VERDICT r4 ruled the
                        # resulting "CPU-default FINAL" premature because
                        # it generalized tunnel-biased data. The model:
                        # through the axon tunnel (sync round-trip
                        # 85-150 ms, H2D ~1.1 GB/s) a 1 MB/16-part
                        # offload call pays >=85 ms RTT, capping ANY
                        # tunneled hash kernel at ~8-11 MB/s — the
                        # tunnel, not the kernel, sets that number
                        # (measured r3: offload 2.28 vs CPU 205 MB/s).
                        # On a locally attached chip the cap vanishes and
                        # the question becomes compression-chain
                        # serialism (a 64 KB part = 1024 strictly
                        # sequential SHA/RIPEMD rounds, parallel only
                        # across parts, no MXU help) vs the host AVX-512
                        # path (~1.2 GB/s ripemd160_x16) — an empirical
                        # question this bench answers wherever it runs
                        # with a local chip; no such environment has been
                        # available yet (the driver reaches the chip
                        # through the tunnel).
                        "tunnel_rtt_s": [0.085, 0.150],
                        "tunnel_h2d_gb_s": 1.1,
                        "tunneled_cap_mb_s": [8, 11],
                        "cpu_openssl_mb_s_per_core": 200,
                    },
                    "platform": platform_label(),
                    "offload_stats": tpu.stats(),
                    "parity": "ok",
                    "proofs": "verified",
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
