"""Multi-process testnet harness (reference: test/p2p/* runs the same
scenarios in docker containers; this tier runs them as real node
PROCESSES over real TCP — same isolation properties that matter for the
scenarios: separate interpreters, separate homes/DBs/WALs, kill -9
crash semantics, reconnection over sockets).

Used by scenarios.py (basic, atomic_broadcast, fast_sync, kill_all,
seeds, pex) and
the pytest wrapper tests/test_localnet.py. Where docker IS available,
test/p2p/Dockerfile + run_docker.sh wrap the same scenarios in
containers.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class Node:
    def __init__(self, home: str, index: int, p2p_port: int, rpc_port: int):
        self.home = home
        self.index = index
        self.p2p_port = p2p_port
        self.rpc_port = rpc_port
        self.proc: subprocess.Popen | None = None

    def start(self, seeds: str = "", extra: list[str] | None = None) -> None:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.setdefault("TENDERMINT_TPU_DISABLE", "1")
        env["PYTHONPATH"] = REPO
        cmd = [
            sys.executable, "-m", "tendermint_tpu.cli",
            "--home", self.home, "node",
            "--proxy_app", "kvstore",
            "--p2p.laddr", f"tcp://127.0.0.1:{self.p2p_port}",
            "--rpc.laddr", f"tcp://127.0.0.1:{self.rpc_port}",
            "--log_level", "warning",
        ]
        if seeds:
            cmd += ["--seeds", seeds]
        cmd += extra or []
        self.proc = subprocess.Popen(
            cmd,
            cwd=REPO,
            env=env,
            stdout=open(os.path.join(self.home, "node.log"), "ab"),
            stderr=subprocess.STDOUT,
        )

    def rpc(self, method: str, params: dict | None = None, timeout: float = 30):
        body = json.dumps(
            {"jsonrpc": "2.0", "id": "ln", "method": method, "params": params or {}}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.rpc_port}/", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
        if out.get("error"):
            raise RuntimeError(f"node{self.index} {method}: {out['error']}")
        return out["result"]

    def height(self) -> int:
        try:
            return int(self.rpc("status")["latest_block_height"])
        except Exception:  # noqa: BLE001 — down/starting counts as 0
            return -1

    def kill(self, sig=signal.SIGKILL) -> None:
        if self.proc is None:
            return
        try:
            self.proc.send_signal(sig)
            self.proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 — a wedged shutdown escalates:
            # dropping the handle would orphan a process on bound ports
            try:
                self.proc.kill()
                self.proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
        self.proc = None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Localnet:
    def __init__(self, n: int, root: str, base_port: int = 46900):
        self.root = root
        self.nodes: list[Node] = []
        # shared genesis via the CLI's own testnet command
        subprocess.run(
            [
                sys.executable, "-m", "tendermint_tpu.cli", "testnet",
                "--n", str(n), "--dir", root, "--chain-id", "localnet",
            ],
            cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                 "TENDERMINT_TPU_DISABLE": "1"},
            check=True,
            capture_output=True,
        )
        for i in range(n):
            self.nodes.append(
                Node(os.path.join(root, f"mach{i}"), i, base_port + 2 * i, base_port + 2 * i + 1)
            )

    def seeds_for(self, index: int) -> str:
        return ",".join(
            f"127.0.0.1:{nd.p2p_port}" for nd in self.nodes if nd.index != index
        )

    def start_all(self) -> None:
        for nd in self.nodes:
            nd.start(seeds=self.seeds_for(nd.index))

    def stop_all(self) -> None:
        for nd in self.nodes:
            nd.kill(signal.SIGTERM)

    def wait_height(self, h: int, timeout: float = 120, nodes=None) -> bool:
        nodes = nodes if nodes is not None else self.nodes
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(nd.height() >= h for nd in nodes):
                return True
            time.sleep(0.5)
        return False

    def heights(self) -> list[int]:
        return [nd.height() for nd in self.nodes]

    def block_hash(self, index: int, height: int) -> str:
        meta = self.nodes[index].rpc("block", {"height": height})["block_meta"]
        return meta["block_id"]["hash"]

    def assert_chains_agree(self, upto: int) -> None:
        for h in range(1, upto + 1):
            hashes = {self.block_hash(i, h) for i in range(len(self.nodes))}
            assert len(hashes) == 1, f"nodes disagree at height {h}: {hashes}"
