"""The multi-machine scenarios (reference: test/p2p/{basic,
atomic_broadcast,fast_sync,kill_all,pex,seeds}), runnable against a
process-based Localnet — or, via run_docker.sh, against containers.

Each scenario takes a started-or-startable Localnet and raises
AssertionError on failure. `python test/p2p/scenarios.py [name...]`
runs them standalone.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from localnet import Localnet  # noqa: E402


def basic(net: Localnet) -> None:
    """Every node makes progress and the chains are identical
    (test/p2p/basic/test.sh)."""
    net.start_all()
    assert net.wait_height(3), f"no progress: {net.heights()}"
    net.assert_chains_agree(3)


def atomic_broadcast(net: Localnet) -> None:
    """A tx sent to one node commits on every node
    (test/p2p/atomic_broadcast/test.sh)."""
    net.start_all()
    assert net.wait_height(1), net.heights()
    tx = b"atomic=broadcast"
    res = net.nodes[0].rpc("broadcast_tx_commit", {"tx": tx.hex()}, timeout=60)
    assert res["deliver_tx"]["code"] == 0, res
    key = b"atomic".hex()
    deadline = time.monotonic() + 60
    missing = set(range(len(net.nodes)))
    while time.monotonic() < deadline and missing:
        for i in list(missing):
            try:
                q = net.nodes[i].rpc("abci_query", {"data": key})
                if bytes.fromhex(q["response"]["value"] or "") == b"broadcast":
                    missing.discard(i)
            except Exception:  # noqa: BLE001 — still syncing
                pass
        time.sleep(0.5)
    assert not missing, f"nodes {missing} never saw the tx"


def fast_sync(net: Localnet) -> None:
    """Kill one node, let the others advance, restart it, it catches up
    (test/p2p/fast_sync/test.sh)."""
    net.start_all()
    assert net.wait_height(2), net.heights()
    straggler = net.nodes[-1]
    straggler.kill()  # SIGKILL: a crash, not a clean stop
    others = net.nodes[:-1]
    target = max(nd.height() for nd in others) + 6
    assert net.wait_height(target, nodes=others), net.heights()
    straggler.start(seeds=net.seeds_for(straggler.index))
    assert net.wait_height(target, nodes=[straggler], timeout=120), (
        f"straggler at {straggler.height()}, target {target}"
    )
    net.assert_chains_agree(target)


def kill_all(net: Localnet) -> None:
    """Kill every node, restart, the chain continues from persisted state
    (test/p2p/kill_all/test.sh)."""
    net.start_all()
    assert net.wait_height(3), net.heights()
    pre = max(net.heights())
    for nd in net.nodes:
        nd.kill()  # SIGKILL across the board
    time.sleep(1)
    for nd in net.nodes:
        nd.start(seeds=net.seeds_for(nd.index))
    assert net.wait_height(pre + 3, timeout=180), (
        f"no post-restart progress past {pre}: {net.heights()}"
    )
    net.assert_chains_agree(pre + 3)


def seeds(net: Localnet) -> None:
    """Star bootstrap: every node dials ONLY node 0 as its seed; gossip
    relays through the hub and the whole net still commits identical
    chains (test/p2p/seeds.sh)."""
    hub = net.nodes[0]
    hub.start()
    for nd in net.nodes[1:]:
        nd.start(seeds=f"127.0.0.1:{hub.p2p_port}")
    assert net.wait_height(3, timeout=180), f"star net stuck: {net.heights()}"
    net.assert_chains_agree(3)


def pex(net: Localnet) -> None:
    """Peer discovery: same star seeding, but with the PEX reactor on —
    nodes must LEARN the other peers through the hub and form a denser
    mesh (> 1 peer each), and the chain advances
    (test/p2p/pex/test.sh)."""
    hub = net.nodes[0]
    pex_args = ["--pex", "--p2p.addr_book_strict", "false"]
    hub.start(extra=pex_args)
    for nd in net.nodes[1:]:
        nd.start(seeds=f"127.0.0.1:{hub.p2p_port}", extra=pex_args)
    assert net.wait_height(2, timeout=180), f"pex net stuck: {net.heights()}"
    deadline = time.monotonic() + 120
    dense = set()
    while time.monotonic() < deadline and len(dense) < len(net.nodes) - 1:
        for nd in net.nodes[1:]:
            try:
                if len(nd.rpc("net_info")["peers"]) > 1:
                    dense.add(nd.index)
            except Exception:  # noqa: BLE001 — still starting
                pass
        time.sleep(1)
    assert len(dense) >= len(net.nodes) - 1, (
        f"pex never densified the mesh: {sorted(dense)} of "
        f"{[nd.index for nd in net.nodes[1:]]}"
    )


SCENARIOS = {
    "basic": basic,
    "atomic_broadcast": atomic_broadcast,
    "fast_sync": fast_sync,
    "kill_all": kill_all,
    "seeds": seeds,
    "pex": pex,
}


def main(names: list[str]) -> int:
    failed = []
    for name in names or list(SCENARIOS):
        fn = SCENARIOS[name]
        root = tempfile.mkdtemp(prefix=f"localnet-{name}-")
        net = Localnet(4, root, base_port=46900 + 20 * (list(SCENARIOS).index(name)))
        print(f"== {name} ({root})", file=sys.stderr)
        try:
            fn(net)
            print(f"   ok", file=sys.stderr)
        except AssertionError as exc:
            failed.append(name)
            print(f"   FAILED: {exc}", file=sys.stderr)
        finally:
            net.stop_all()
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
