#!/usr/bin/env bash
# N-container testnet running the same scenarios as scenarios.py
# (reference: test/p2p/test.sh). Requires docker; the process-based tier
# (python test/p2p/scenarios.py) covers environments without it.
set -euo pipefail
cd "$(dirname "$0")/../.."
N=${N:-4}
NET=tendermint-tpu-net
docker build -t tendermint-tpu -f test/p2p/Dockerfile .
docker network create "$NET" 2>/dev/null || true
rm -rf /tmp/tm-docker-testnet
PYTHONPATH=. python -m tendermint_tpu.cli testnet --n "$N" --dir /tmp/tm-docker-testnet --chain-id dockernet
SEEDS=$(for i in $(seq 0 $((N-1))); do printf "node%d:46656," "$i"; done | sed 's/,$//')
for i in $(seq 0 $((N-1))); do
  docker run -d --name "node$i" --network "$NET" \
    -v "/tmp/tm-docker-testnet/mach$i:/home" \
    tendermint-tpu --home /home node --proxy_app kvstore \
    --p2p.laddr tcp://0.0.0.0:46656 --rpc.laddr tcp://0.0.0.0:46657 \
    --seeds "$SEEDS"
done
echo "testnet up: docker logs node0 ... node$((N-1))"
