"""How much of the 163ms XLA verify is the 16-entry one-hot table select?
Compare: real kernel vs fixed-addend kernel vs where-tree select variant."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.ops import ed25519 as E

B = 8192
NLIMB = E.NLIMB
REPS = 6


def sustained(fn, args):
    np.asarray(fn(*args))
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(REPS)]
    [np.asarray(o) for o in outs]
    return (time.perf_counter() - t0) / REPS


def make_variant(select_mode: str):
    def impl(ax, ay, r_y, r_sign, s_limbs, h_limbs):
        batch = ax.shape[-1]
        zeros = jnp.zeros((NLIMB, batch), dtype=jnp.int32)
        one = zeros.at[0].set(1)

        def const_pt(xc, yc):
            x = jnp.broadcast_to(jnp.asarray(xc)[:, None], (NLIMB, batch))
            y = jnp.broadcast_to(jnp.asarray(yc)[:, None], (NLIMB, batch))
            return (x, y, one, E.fmul(x, y))

        nax = E.fsub(zeros, ax)
        neg_a = (nax, ay, one, E.fmul(nax, ay))
        na2 = E.point_double(neg_a)
        na3 = E.point_add(na2, neg_a)
        ident = E._identity(batch)
        b_row = [ident, const_pt(E._BX, E._BY), const_pt(E._B2X, E._B2Y), const_pt(E._B3X, E._B3Y)]
        a_row = [ident, neg_a, na2, na3]
        table = []
        for j in range(4):
            for i in range(4):
                if i == 0:
                    table.append(a_row[j])
                elif j == 0:
                    table.append(b_row[i])
                else:
                    table.append(E.point_add(b_row[i], a_row[j]))
        tcoords = [jnp.stack([t[c] for t in table], axis=0) for c in range(4)]

        xs = jnp.stack(
            [E._digits2_from_limbs(s_limbs), E._digits2_from_limbs(h_limbs)], axis=1
        )
        idx16 = jnp.arange(16, dtype=jnp.int32)

        def step(acc, dig):
            acc = E.point_double(E.point_double(acc))
            sel = dig[0] + 4 * dig[1]
            if select_mode == "onehot":
                onehot = (sel[None, :] == idx16[:, None]).astype(jnp.int32)
                addend = tuple(jnp.sum(onehot[:, None, :] * tc, axis=0) for tc in tcoords)
            elif select_mode == "fixed":
                addend = tuple(tc[1] for tc in tcoords)
            elif select_mode == "wheretree":
                b0 = (sel & 1)[None, :].astype(bool)
                b1 = (sel & 2)[None, :].astype(bool)
                b2 = (sel & 4)[None, :].astype(bool)
                b3 = (sel & 8)[None, :].astype(bool)
                addend = []
                for tc in tcoords:
                    lvl = [jnp.where(b0, tc[2 * i + 1], tc[2 * i]) for i in range(8)]
                    lvl = [jnp.where(b1, lvl[2 * i + 1], lvl[2 * i]) for i in range(4)]
                    lvl = [jnp.where(b2, lvl[2 * i + 1], lvl[2 * i]) for i in range(2)]
                    addend.append(jnp.where(b3, lvl[1], lvl[0]))
                addend = tuple(addend)
            return E.point_add(acc, addend), None

        acc, _ = jax.lax.scan(step, ident, xs)
        px, py, pz, _ = acc
        zinv = E.finv(pz)
        x_aff = E.fcanon(E.fmul(px, zinv))
        y_aff = E.fcanon(E.fmul(py, zinv))
        sign = x_aff[0] & 1
        return jnp.all(y_aff == E.fcanon(r_y), axis=0) & (sign == r_sign)

    return jax.jit(impl)


def main():
    from tendermint_tpu.crypto import ed25519 as ed

    print(jax.devices()[0], file=sys.stderr)
    seeds = [bytes([i]) * 32 for i in range(8)]
    pubs = [ed.public_key(s) for s in seeds]
    items = []
    for i in range(B):
        k = i % 8
        m = b"m%d" % i
        items.append((pubs[k], m, ed.sign(seeds[k], m)))
    prep = E.prepare_batch_limbs(items, B)
    args = tuple(jax.device_put(np.asarray(a)) for a in prep[:6])

    for mode in ("onehot", "wheretree", "fixed"):
        fn = make_variant(mode)
        el = sustained(fn, args)
        ok = np.asarray(fn(*args))
        note = "" if mode == "fixed" else f" all-ok={bool(ok.all())}"
        print(f"{mode}: {el*1e3:.1f} ms/batch{note}")


if __name__ == "__main__":
    main()
