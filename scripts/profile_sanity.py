"""Sanity-check the fmul scan timing: scaling with K and output dependence."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.ops import ed25519 as E

B = 8192
NL = E.NLIMB


def main():
    print(jax.devices()[0], file=sys.stderr)
    key = jax.random.PRNGKey(0)
    a = jax.random.randint(key, (NL, B), 0, 32768, dtype=jnp.int32)
    b = jax.random.randint(key, (NL, B), 0, 32768, dtype=jnp.int32)

    def make(K):
        @jax.jit
        def fmul_scan(a, b):
            def body(x, _):
                return E.fmul(x, b), None
            x, _ = jax.lax.scan(body, a, None, length=K)
            return x
        return fmul_scan

    for K in (50, 200, 800):
        fn = make(K)
        np.asarray(fn(a, b))
        t0 = time.perf_counter()
        for _ in range(10):
            o = fn(a, b)
            np.asarray(o)  # force full sync via host readback
        el = (time.perf_counter() - t0) / 10
        print(f"K={K}: {el*1e3:.3f} ms total, {el/K*1e6:.2f} us/fmul")

    # correctness: does one fmul match the CPU big-int multiply?
    av = np.asarray(a[:, 0])
    bv = np.asarray(b[:, 0])
    ai = E.limbs_to_int(av)
    bi = E.limbs_to_int(bv)
    out = np.asarray(E.fmul(a, b))
    got = E.limbs_to_int(E.fcanon(jnp.asarray(out))[:, 0]) if False else None
    got_i = E.limbs_to_int(np.asarray(E.fcanon(E.fmul(a, b)))[:, 0])
    print("fmul correct:", got_i == (ai * bi) % E.P)


if __name__ == "__main__":
    main()
