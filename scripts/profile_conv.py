"""Can the limb-product convolution run as lax.conv (MXU)? Check exactness
with adversarial max-bound limbs and measure speed."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.crypto import ed25519 as ed

P = ed.P
NL = 32
R = 256.0
RINV = 1.0 / 256.0


def _roll38(hi):
    return jnp.concatenate([38.0 * hi[NL - 1:], hi[: NL - 1]], axis=0)


def _carry1(x):
    hi = jnp.floor(x * RINV)
    return x - hi * R + _roll38(hi)


def fmul_conv(a, b):
    # a, b: (32, B) f32. Depthwise conv: channels = batch, spatial = limbs.
    # c[k] = sum_i a[i] * b[k-i], k in 0..62 (full correlation output)
    Bn = a.shape[-1]
    lhs = a.T[None]  # (1, B, 32)  NCW
    rhs = b.T[:, None, ::-1]  # (B, 1, 32) OIW, reversed for convolution
    out = jax.lax.conv_general_dilated(
        lhs, rhs,
        window_strides=(1,),
        padding=[(31, 31)],
        feature_group_count=Bn,
        dimension_numbers=("NCW", "OIW", "NCW"),
        precision=jax.lax.Precision.HIGHEST,
    )  # (1, B, 63)
    rows = out[0].T  # (63, B)
    t = rows[NL:]
    t_hi = jnp.floor(t * RINV)
    t_lo = t - t_hi * R
    out32 = rows[:NL]
    out32 = out32.at[:31].add(38.0 * t_lo)
    out32 = out32.at[1:32].add(38.0 * t_hi)
    return _carry1(_carry1(_carry1(out32)))


def limbs_to_int(col):
    return sum(int(round(float(col[k]))) << (8 * k) for k in range(NL))


def main():
    print(jax.devices()[0], file=sys.stderr)
    B = 8192
    rng = np.random.default_rng(1)

    # adversarial: limbs at the loose-bound maxima (749 limb0, 268 others)
    a_np = rng.integers(0, 268, (NL, B)).astype(np.float32)
    b_np = rng.integers(0, 268, (NL, B)).astype(np.float32)
    a_np[0] = rng.integers(600, 750, B)
    b_np[0] = rng.integers(600, 750, B)
    a = jnp.asarray(a_np)
    b = jnp.asarray(b_np)

    fn = jax.jit(fmul_conv)
    t0 = time.perf_counter()
    out = np.asarray(fn(a, b))
    print(f"compile: {time.perf_counter()-t0:.1f}s")

    ok = True
    for i in range(64):
        ai = limbs_to_int(a_np[:, i])
        bi = limbs_to_int(b_np[:, i])
        got = limbs_to_int(out[:, i]) % P
        if got != (ai * bi) % P:
            ok = False
            print(f"MISMATCH lane {i}")
            break
    print("exact:", ok, "| max limb:", out.max())

    # speed: scan chain slope
    def make(K):
        @jax.jit
        def chain(a, b):
            def body(x, _):
                return fmul_conv(x, b), None
            x, _ = jax.lax.scan(body, a, None, length=K)
            return x
        return chain

    f1, f2 = make(100), make(400)
    np.asarray(f1(a, b)); np.asarray(f2(a, b))
    reps = 6
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(f1(a, b))
    e1 = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(f2(a, b))
    e2 = (time.perf_counter() - t0) / reps
    print(f"conv fmul: {(e2-e1)/300*1e6:.1f} us/fmul (chain slope)")


if __name__ == "__main__":
    main()
