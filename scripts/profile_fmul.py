"""fmul formulation shootout: .at.add accumulator (current) vs per-limb sum
DAG vs Karatsuba vs fp32 radix-2^9. Measures marginal us/fmul via scan-chain
slope (K=200 vs K=800) with forced readback sync."""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.ops import ed25519 as E

B = 8192
NL = E.NLIMB
M15 = E.M15


# ---- variant 1: current
fmul_cur = E.fmul


# ---- variant 2: per-limb sum DAG (no .at.add)
def fmul_dag(a, b):
    lo, hi = [], []
    for i in range(NL):
        p = a[i][None, :] * b
        lo.append(p & M15)
        hi.append(p >> 15)
    rows = []
    for k in range(34):
        terms = []
        for i in range(NL):
            j = k - i
            if 0 <= j < NL:
                terms.append(lo[i][j])
            j2 = k - 1 - i
            if 0 <= j2 < NL:
                terms.append(hi[i][j2])
        s = terms[0]
        for t in terms[1:]:
            s = s + t
        rows.append(s)
    res = jnp.stack([rows[k] + 19 * rows[k + NL] for k in range(NL)], axis=0)
    return E._carry(res)


# ---- variant 3: fp32 radix-2^9 (29 limbs), carry with floor
NL9 = 29
R9 = 512.0
M9 = 511


def _carry9(x):
    # two parallel passes; top limb folds with 19 * 2^(-(255 - 28*9)) ... using
    # radix 2^9 and 29 limbs = 261 bits; fold limb 29+ weight 2^261 = 2^6*19...
    # for the shootout only the THROUGHPUT matters; math checked separately.
    hi = jnp.floor(x / R9)
    y = x - hi * R9 + jnp.concatenate([19.0 * hi[NL9 - 1:], hi[: NL9 - 1]], axis=0)
    hi2 = jnp.floor(y / R9)
    return y - hi2 * R9 + jnp.concatenate([19.0 * hi2[NL9 - 1:], hi2[: NL9 - 1]], axis=0)


def fmul_f32(a, b):
    acc = jnp.zeros((2 * NL9, a.shape[-1]), dtype=jnp.float32)
    for i in range(NL9):
        acc = acc.at[i: i + NL9].add(a[i][None, :] * b)
    res = acc[:NL9] + 19.0 * acc[NL9:]
    return _carry9(res)


def fmul_f32_dag(a, b):
    prods = [a[i][None, :] * b for i in range(NL9)]
    rows = []
    for k in range(2 * NL9 - 1):
        terms = []
        for i in range(NL9):
            j = k - i
            if 0 <= j < NL9:
                terms.append(prods[i][j])
        s = terms[0]
        for t in terms[1:]:
            s = s + t
        rows.append(s)
    rows.append(jnp.zeros_like(rows[0]))
    res = jnp.stack([rows[k] + 19.0 * rows[k + NL9] for k in range(NL9)], axis=0)
    return _carry9(res)


def slope(fn, a, b, K1=200, K2=800):
    def make(K):
        @jax.jit
        def chain(a, b):
            def body(x, _):
                return fn(x, b), None
            x, _ = jax.lax.scan(body, a, None, length=K)
            return x
        return chain

    f1, f2 = make(K1), make(K2)
    np.asarray(f1(a, b)); np.asarray(f2(a, b))
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(f1(a, b))
    e1 = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(f2(a, b))
    e2 = (time.perf_counter() - t0) / reps
    return (e2 - e1) / (K2 - K1) * 1e6


def main():
    print(jax.devices()[0], file=sys.stderr)
    key = jax.random.PRNGKey(0)
    a = jax.random.randint(key, (NL, B), 0, 32768, dtype=jnp.int32)
    b = jax.random.randint(key, (NL, B), 0, 32768, dtype=jnp.int32)
    a9 = jax.random.randint(key, (NL9, B), 0, 512, dtype=jnp.int32).astype(jnp.float32)
    b9 = jax.random.randint(key, (NL9, B), 0, 512, dtype=jnp.int32).astype(jnp.float32)

    print(f"int32 .at.add (current): {slope(fmul_cur, a, b):.1f} us/fmul")
    print(f"int32 per-limb DAG:      {slope(fmul_dag, a, b):.1f} us/fmul")
    print(f"fp32 r512 .at.add:       {slope(fmul_f32, a9, b9):.1f} us/fmul")
    print(f"fp32 r512 DAG:           {slope(fmul_f32_dag, a9, b9):.1f} us/fmul")


if __name__ == "__main__":
    main()
