"""RSS soak harness (VERDICT r4 #7): run a 4-node localnet under
continuous tx load, sample per-node RSS, and assert a ~flat post-warmup
slope. Periodically captures heap profiles through the unsafe RPC route
(rpc/core/handlers.unsafe_write_heap_profile — the reference's
rpc/core/dev.go:24-38 equivalent) so any residual growth is NAMED, not
just measured.

Usage:  python scripts/soak_rss.py [--minutes 60] [--nodes 4]
Writes: <outdir>/soak_rss.json  (samples, slope, top heap growers)

Slope methodology: least-squares on RSS(t) for t past the warmup cutoff
(first 25% of the run), per node, in KB/min. "Flat" is < 1% of final
RSS per 10 minutes — caches (tx LRU, addrbook, block store index) fill
early and must then hold steady.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
# localnet lives beside the scenarios (same import style scenarios.py
# uses — the stdlib `test` package shadows a `test.p2p` package path)
sys.path.insert(0, os.path.join(_REPO, "test", "p2p"))

from localnet import Localnet  # noqa: E402


def rss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


def slope_kb_per_min(samples: list[tuple[float, int]]) -> float:
    """Least-squares slope of (t_seconds, rss_kb) -> KB/min."""
    n = len(samples)
    if n < 2:
        return 0.0
    ts = [s[0] / 60.0 for s in samples]
    ys = [float(s[1]) for s in samples]
    tm = sum(ts) / n
    ym = sum(ys) / n
    denom = sum((t - tm) ** 2 for t in ts)
    if denom == 0:
        return 0.0
    return sum((t - tm) * (y - ym) for t, y in zip(ts, ys)) / denom


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=60.0)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--sample-s", type=float, default=15.0)
    ap.add_argument("--outdir", default=None)
    args = ap.parse_args()

    root = args.outdir or tempfile.mkdtemp(prefix="soak-rss-")
    # own port range: the 46900 default collides with other harness runs
    net = Localnet(args.nodes, root, base_port=47300)
    # unsafe routes on so the heap profiler is reachable mid-soak
    for nd in net.nodes:
        nd.start(seeds=net.seeds_for(nd.index), extra=["--rpc.unsafe"])
    print(f"soak: {args.nodes} nodes under {root}, {args.minutes} min")
    if not net.wait_height(1, timeout=180):
        print("FATAL: net never reached height 1")
        net.stop_all()
        return 1

    t0 = time.monotonic()
    end = t0 + args.minutes * 60
    samples: dict[int, list[tuple[float, int]]] = {
        nd.index: [] for nd in net.nodes
    }
    heights: list[tuple[float, int]] = []
    tx_n = 0
    heap_paths: list[str] = []
    next_heap = t0 + args.minutes * 60 * 0.5  # one mid-run heap profile
    while time.monotonic() < end:
        # continuous light tx load round-robins the nodes
        for _ in range(16):
            nd = net.nodes[tx_n % len(net.nodes)]
            try:
                nd.rpc(
                    "broadcast_tx_async",
                    {"tx": (b"soak%08d=x" % tx_n).hex()},
                    timeout=10,
                )
            except Exception:  # noqa: BLE001 — a busy node skips a beat
                pass
            tx_n += 1
        now = time.monotonic()
        for nd in net.nodes:
            if nd.alive():
                try:
                    samples[nd.index].append((now - t0, rss_kb(nd.proc.pid)))
                except OSError:
                    pass
        heights.append((now - t0, max(nd.height() for nd in net.nodes)))
        if now >= next_heap:
            next_heap = float("inf")
            for nd in net.nodes[:1]:  # one node's heap is representative
                p = os.path.join(root, f"heap-mid-node{nd.index}.txt")
                try:
                    nd.rpc("unsafe_write_heap_profile", {"filename": p})
                    heap_paths.append(p)
                    print(f"  heap profile written: {p}")
                except Exception as exc:  # noqa: BLE001
                    print(f"  heap profile failed: {exc}")
        time.sleep(max(0.0, args.sample_s - (time.monotonic() - now)))

    # end-of-run heap profile for the same node (diffable against mid)
    for nd in net.nodes[:1]:
        p = os.path.join(root, f"heap-end-node{nd.index}.txt")
        try:
            nd.rpc("unsafe_write_heap_profile", {"filename": p})
            heap_paths.append(p)
        except Exception as exc:  # noqa: BLE001
            print(f"  end heap profile failed: {exc}")
    net.stop_all()

    warm_cut = args.minutes * 60 * 0.25
    report: dict = {
        "minutes": args.minutes,
        "nodes": args.nodes,
        "txs_sent": tx_n,
        "final_height": heights[-1][1] if heights else 0,
        "heap_profiles": heap_paths,
        "per_node": {},
    }
    # node0 carries the mid-run heap profile: starting tracemalloc adds
    # ~50 KB/min of TRACKING overhead to that node (measured: the traced
    # node ran ~+50 KB/min above its untraced peers in both the filedb
    # and sqlite soaks), so the observer is excluded from the aggregate
    # flatness verdict — its profile is the naming tool, its slope is
    # reported but not asserted.
    traced = net.nodes[0].index
    report["traced_node"] = traced
    ok = True
    for idx, ss in samples.items():
        post = [s for s in ss if s[0] >= warm_cut]
        sl = slope_kb_per_min(post)
        final = ss[-1][1] if ss else 0
        # flat = < 1% of final RSS per 10 min of post-warmup runtime
        limit = 0.001 * final  # KB/min
        flat = abs(sl) < max(limit, 50.0)
        if idx != traced:
            ok = ok and flat
        report["per_node"][idx] = {
            "final_rss_kb": final,
            "post_warmup_slope_kb_per_min": round(sl, 1),
            "flat_limit_kb_per_min": round(max(limit, 50.0), 1),
            "flat": flat,
            "traced": idx == traced,
            "samples": len(ss),
        }
        print(
            f"node{idx}: final {final/1024:.0f} MB, post-warmup slope "
            f"{sl:+.1f} KB/min "
            f"({'flat' if flat else 'GROWING'}"
            f"{', tracemalloc observer' if idx == traced else ''})"
        )
    report["flat"] = ok
    out = os.path.join(root, "soak_rss.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"report: {out}  flat={ok}")
    return 0 if ok else 2


if __name__ == "__main__":
    sys.exit(main())
