"""Sustained back-to-back kernel throughput: enqueue N executions, sync once.
This is what a pipelined verifier achieves when transfers/marshal overlap."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.ops import ed25519 as E
from tendermint_tpu.ops import ed25519_pallas as EP
from tendermint_tpu.crypto import ed25519 as ed

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
REPS = 10


def main():
    print(jax.devices()[0], file=sys.stderr)
    seeds = [bytes([i]) * 32 for i in range(64)]
    pubs = [ed.public_key(s) for s in seeds]
    items = []
    for i in range(B):
        k = i % 64
        msg = b"m%d-%d" % (i, k)
        items.append((pubs[k], msg, ed.sign(seeds[k], msg)))

    # ---- XLA kernel
    prep = E.prepare_batch_limbs(items, B)
    dev_args = tuple(jax.device_put(np.asarray(a)) for a in prep[:6])
    ok = np.asarray(E._verify_jit(*dev_args))
    assert ok[: len(items)].all()
    t0 = time.perf_counter()
    outs = [E._verify_jit(*dev_args) for _ in range(REPS)]
    res = [np.asarray(o) for o in outs]
    el = (time.perf_counter() - t0) / REPS
    print(f"xla sustained: {el*1e3:.1f} ms/batch = {B/el:.0f} sigs/s")

    # ---- Pallas kernel
    s_total = B // 128
    ax, ay, ry, rs, s_bits, h_bits, valid = E.prepare_batch(items, B)
    s_rev = np.ascontiguousarray(s_bits[::-1]).reshape(253, s_total, 128)
    h_rev = np.ascontiguousarray(h_bits[::-1]).reshape(253, s_total, 128)
    args = (
        jax.device_put(ax.reshape(E.NLIMB, s_total, 128)),
        jax.device_put(ay.reshape(E.NLIMB, s_total, 128)),
        jax.device_put(ry.reshape(E.NLIMB, s_total, 128)),
        jax.device_put(rs.reshape(1, s_total, 128).astype(np.int32)),
        jax.device_put(s_rev),
        jax.device_put(h_rev),
    )
    fn = EP._get_verify(EP.S_TILE, False)
    np.asarray(fn(*args))
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(REPS)]
    res = [np.asarray(o) for o in outs]
    el = (time.perf_counter() - t0) / REPS
    print(f"pallas sustained: {el*1e3:.1f} ms/batch = {B/el:.0f} sigs/s")


if __name__ == "__main__":
    main()
