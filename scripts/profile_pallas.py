"""Device-resident compute time of the Pallas verify kernel + XLA one-hot
select cost check."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.ops import ed25519 as E
from tendermint_tpu.ops import ed25519_pallas as EP
from tendermint_tpu.crypto import ed25519 as ed

B = 8192


def t(msg, f, reps=3):
    f()
    t0 = time.perf_counter()
    for _ in range(reps):
        f()
    el = (time.perf_counter() - t0) / reps
    print(f"{msg}: {el*1e3:.1f} ms")
    return el


def main():
    print(jax.devices()[0], file=sys.stderr)
    seeds = [bytes([i]) * 32 for i in range(64)]
    pubs = [ed.public_key(s) for s in seeds]
    items = []
    for i in range(B):
        k = i % 64
        msg = b"m%d-%d" % (i, k)
        items.append((pubs[k], msg, ed.sign(seeds[k], msg)))

    s_total = B // 128
    ax, ay, ry, rs, s_bits, h_bits, valid = E.prepare_batch(items, B)
    s_rev = np.ascontiguousarray(s_bits[::-1]).reshape(253, s_total, 128)
    h_rev = np.ascontiguousarray(h_bits[::-1]).reshape(253, s_total, 128)
    args = (
        jax.device_put(ax.reshape(E.NLIMB, s_total, 128)),
        jax.device_put(ay.reshape(E.NLIMB, s_total, 128)),
        jax.device_put(ry.reshape(E.NLIMB, s_total, 128)),
        jax.device_put(rs.reshape(1, s_total, 128).astype(np.int32)),
        jax.device_put(s_rev),
        jax.device_put(h_rev),
    )
    fn = EP._get_verify(EP.S_TILE, False)
    ok = np.asarray(fn(*args))
    assert (ok.reshape(-1)[: len(items)] != 0).all()
    t("pallas verify: device-resident", lambda: np.asarray(fn(*args)))


if __name__ == "__main__":
    main()
