"""Dev tool: time individual field/point ops of the jnp Ed25519 kernel to
find where the 405ms/batch goes."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.ops import ed25519 as E

B = 8192
NL = E.NLIMB


def bench(name, fn, *args, reps=20):
    o = fn(*args)
    jax.block_until_ready(o)
    t0 = time.perf_counter()
    for _ in range(reps):
        o = fn(*args)
    jax.block_until_ready(o)
    el = (time.perf_counter() - t0) / reps
    print(f"{name}: {el*1e3:.2f} ms")
    return el


def main():
    print(jax.devices()[0], file=sys.stderr)
    key = jax.random.PRNGKey(0)
    a = jax.random.randint(key, (NL, B), 0, 32768, dtype=jnp.int32)
    b = jax.random.randint(key, (NL, B), 0, 32768, dtype=jnp.int32)

    K = 100

    @jax.jit
    def fmul_scan(a, b):
        def body(x, _):
            return E.fmul(x, b), None
        x, _ = jax.lax.scan(body, a, None, length=K)
        return x

    @jax.jit
    def carry_scan(a):
        def body(x, _):
            return E._carry(x + 7), None
        x, _ = jax.lax.scan(body, a, None, length=K)
        return x

    pt = (a, b, a, b)

    @jax.jit
    def dbl_scan(pt):
        def body(p, _):
            return E.point_double(p), None
        p, _ = jax.lax.scan(body, pt, None, length=K)
        return p[0]

    t = bench(f"fmul x{K} scan", fmul_scan, a, b)
    print(f"  -> per fmul: {t/K*1e6:.0f} us ; ladder(3440 fmul) est {t/K*3440*1e3:.0f} ms")
    t = bench(f"carry x{K} scan", carry_scan, a)
    print(f"  -> per carry: {t/K*1e6:.0f} us")
    t = bench(f"point_double x{K} scan", dbl_scan, pt)
    print(f"  -> per dbl: {t/K*1e6:.0f} us ; 254 dbl est {t/K*254*1e3:.0f} ms")

    # one-hot select cost (16-entry table)
    tc = jax.random.randint(key, (16, NL, B), 0, 32768, dtype=jnp.int32)
    sel = jax.random.randint(key, (B,), 0, 16, dtype=jnp.int32)
    idx16 = jnp.arange(16, dtype=jnp.int32)

    @jax.jit
    def select_chain(tc, sel):
        out = jnp.zeros((NL, B), jnp.int32)
        for i in range(K // 4):
            onehot = ((sel + i) % 16 == idx16[:, None]).astype(jnp.int32)
            out = out + jnp.sum(onehot[:, None, :] * tc, axis=0)
        return out

    t = bench(f"one-hot 16-select x{K//4}", select_chain, tc, sel)
    print(f"  -> per select(x4 coords): {t/(K//4)*4*1e6:.0f} us; 127 steps est {t/(K//4)*4*127*1e3:.0f} ms")


if __name__ == "__main__":
    main()
