"""Dev tool: measure each Ed25519 verify kernel variant on the local device.

Used to pick the production kernel for ops/gateway.py and bench.py.
Prints one JSON line per variant.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tendermint_tpu.jitcache import enable as _enable_jit_cache

_enable_jit_cache()

BATCH = int(os.environ.get("BENCH_BATCH", "8192"))
N_BATCHES = int(os.environ.get("BENCH_N_BATCHES", "4"))


def make_items(n: int):
    from tendermint_tpu.crypto import ed25519 as ed

    seeds = [bytes([i]) * 32 for i in range(64)]
    pubs = [ed.public_key(s) for s in seeds]
    items = []
    for i in range(n):
        k = i % 64
        msg = b'{"chain_id":"bench","height":%d,"vi":%d}' % (1 + i // 64, k)
        items.append((pubs[k], msg, ed.sign(seeds[k], msg)))
    return items


def timed(name, fn, items, n_batches):
    import numpy as np

    # warmup / compile
    t0 = time.perf_counter()
    ok = fn(items)
    compile_s = time.perf_counter() - t0
    assert np.asarray(ok).all(), f"{name}: verify failed"
    t0 = time.perf_counter()
    outs = []
    for _ in range(n_batches):
        outs.append(fn(items))
    res = [np.asarray(o) for o in outs]
    el = time.perf_counter() - t0
    assert all(r.all() for r in res)
    rate = len(items) * n_batches / el
    print(json.dumps({
        "variant": name, "sigs_per_sec": round(rate, 1),
        "batch": len(items), "compile_s": round(compile_s, 1),
        "ms_per_batch": round(1000 * el / n_batches, 1),
    }), flush=True)
    return rate


def main():
    import jax

    print(f"platform: {jax.devices()[0]}", file=sys.stderr)
    items = make_items(BATCH)

    which = sys.argv[1] if len(sys.argv) > 1 else "all"

    if which in ("all", "xla"):
        from tendermint_tpu.ops import ed25519 as ops_ed
        timed("xla_jnp", ops_ed.verify_batch, items, N_BATCHES)
    if which in ("all", "pallas"):
        from tendermint_tpu.ops import ed25519_pallas as ops_pl
        timed("pallas", ops_pl.verify_batch, items, N_BATCHES)


if __name__ == "__main__":
    main()
