"""Correctness + sustained speed of the fp32 verify kernels.

Sweeps the 512-lane mixed valid/tampered/malformed correctness check over
BOTH fp32 backends (f32 conv-composed, f32p pallas — the TPU production
default), then measures each one's sustained device rate at batch 8192
with a single aggregate fetch (per-batch sync fetches pay the tunnel RTT;
see jitcache.probe_device docstring)."""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
import jax
from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.ops import ed25519_f32 as F


def main():
    print(jax.devices()[0], file=sys.stderr)
    # correctness: valid + tampered + malformed lanes
    seeds = [bytes([i]) * 32 for i in range(32)]
    pubs = [ed.public_key(s) for s in seeds]
    items, expect = [], []
    for i in range(512):
        k = i % 32
        m = b"msg-%d" % i
        sig = ed.sign(seeds[k], m)
        if i % 7 == 3:
            bad = bytearray(sig); bad[2] ^= 0x40
            items.append((pubs[k], m, bytes(bad))); expect.append(False)
        elif i % 7 == 5:
            items.append((pubs[k], b"other", sig)); expect.append(False)
        elif i % 11 == 1:
            items.append((b"\x00" * 32, m, sig)); expect.append(ed.verify(b"\x00" * 32, m, sig))
        elif i % 13 == 7:
            bad = bytearray(sig); bad[33] ^= 0x80  # tamper s high bits -> s >= L or wrong
            items.append((pubs[k], m, bytes(bad))); expect.append(ed.verify(pubs[k], m, bytes(bad)))
        else:
            items.append((pubs[k], m, sig)); expect.append(True)
    exp = np.array(expect)
    from tendermint_tpu.ops import ed25519_f32p as FP

    for name, mod in (("f32", F), ("f32p", FP)):
        got = mod.verify_batch(items)
        assert (got == exp).all(), f"{name} mismatch at {np.nonzero(got != exp)}"
        print(
            f"{name} correctness: 512 mixed lanes OK "
            f"({exp.sum()} valid, {(~exp).sum()} invalid)"
        )

    # sustained speed, device-resident
    import jax.numpy as jnp

    B = 8192
    items = []
    for i in range(B):
        k = i % 32
        m = b"m%d" % i
        items.append((pubs[k], m, ed.sign(seeds[k], m)))
    prep = F.prepare_batch8(items, B)
    t0 = time.perf_counter()
    F.prepare_batch8(items, B)
    print(f"marshal: {(time.perf_counter()-t0)*1e3:.0f} ms/batch")
    args = tuple(jax.device_put(np.asarray(a)) for a in prep[:6])
    t0 = time.perf_counter()
    ok = np.asarray(F._verify_jit(*args))
    print(f"compile: {time.perf_counter()-t0:.1f} s")
    assert ok.all()
    REPS = 10
    t0 = time.perf_counter()
    outs = [F._verify_jit(*args) for _ in range(REPS)]
    np.asarray(jnp.stack(outs))  # ONE fetch: per-batch syncs pay tunnel RTT
    el = (time.perf_counter() - t0) / REPS
    print(f"f32 sustained: {el*1e3:.1f} ms/batch = {B/el:.0f} sigs/s")

    # f32p (pallas ladder): SAME protocol — the production marshal runs
    # ONCE (FP.marshal_device_args, the same helper verify_batch_async
    # uses), then only the device call is timed with one aggregate fetch
    pargs, _valid, _n = FP.marshal_device_args(items)
    fnp = FP._get_verify(FP.S_TILE, not FP._on_tpu())
    okp = np.asarray(fnp(*pargs))
    assert (okp.reshape(-1)[:B] != 0).all()
    t0 = time.perf_counter()
    outs = [fnp(*pargs) for _ in range(REPS)]
    np.asarray(jnp.stack(outs))
    el = (time.perf_counter() - t0) / REPS
    print(f"f32p sustained: {el*1e3:.1f} ms/batch = {B/el:.0f} sigs/s")


if __name__ == "__main__":
    main()
