"""Exact fp32 radix-2^8 field mult prototype: correctness vs python ints and
us/fmul at several batch sizes (vs current int32 at same batches)."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.ops import ed25519 as E
from tendermint_tpu.crypto import ed25519 as ed

NL8 = 32
P = ed.P


def int_to_limbs8(vals):
    b = np.zeros((len(vals), 32), dtype=np.uint8)
    for i, v in enumerate(vals):
        b[i] = np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8)
    return np.ascontiguousarray(b.astype(np.float32).T)  # (32, B)


def limbs8_to_int(col):
    return sum(int(round(float(col[k]))) << (8 * k) for k in range(NL8)) % P


def _carry8(x):
    # pass 1
    hi = jnp.floor(x * (1.0 / 256.0))
    lo = x - hi * 256.0
    y = lo + jnp.concatenate([38.0 * hi[NL8 - 1:], hi[: NL8 - 1]], axis=0)
    # pass 2
    hi2 = jnp.floor(y * (1.0 / 256.0))
    lo2 = y - hi2 * 256.0
    return lo2 + jnp.concatenate([38.0 * hi2[NL8 - 1:], hi2[: NL8 - 1]], axis=0)


def fmul8(a, b):
    prods = [a[i][None, :] * b for i in range(NL8)]  # each (32,B), exact <2^18.1
    rows = []
    for k in range(2 * NL8 - 1):
        terms = []
        for i in range(NL8):
            j = k - i
            if 0 <= j < NL8:
                terms.append(prods[i][j])
        s = terms[0]
        for t in terms[1:]:
            s = s + t
        rows.append(s)
    # fold rows k>=32: weight 2^(8k) = 38*2^(8(k-32)) mod p, with hi/lo split
    # so every addend stays < 2^21 (exactness headroom)
    out = list(rows[:NL8])
    for k in range(NL8, 2 * NL8 - 1):
        t = rows[k]
        t_hi = jnp.floor(t * (1.0 / 256.0))
        t_lo = t - t_hi * 256.0
        out[k - NL8] = out[k - NL8] + 38.0 * t_lo
        out[k - NL8 + 1] = out[k - NL8 + 1] + 38.0 * t_hi
    res = jnp.stack(out, axis=0)
    return _carry8(res)


def slope(fn, a, b, K1=100, K2=400):
    def make(K):
        @jax.jit
        def chain(a, b):
            def body(x, _):
                return fn(x, b), None
            x, _ = jax.lax.scan(body, a, None, length=K)
            return x
        return chain

    f1, f2 = make(K1), make(K2)
    np.asarray(f1(a, b)); np.asarray(f2(a, b))
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(f1(a, b))
    e1 = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(f2(a, b))
    e2 = (time.perf_counter() - t0) / reps
    return (e2 - e1) / (K2 - K1) * 1e6


def main():
    print(jax.devices()[0], file=sys.stderr)
    rng = np.random.default_rng(0)

    # correctness: random field elements through a mult chain
    vals_a = [int(rng.integers(0, 2**63 - 1)) for _ in range(8)]
    vals_a = [(v * 0x9E3779B97F4A7C15 + v * v) % P for v in vals_a]
    vals_b = [(v * 0xDEADBEEF12345) % P for v in vals_a]
    a = jnp.asarray(int_to_limbs8(vals_a))
    b = jnp.asarray(int_to_limbs8(vals_b))
    x = a
    ref = list(vals_a)
    for it in range(50):
        x = fmul8(x, b)
        ref = [(r * vb) % P for r, vb in zip(ref, vals_b)]
    xn = np.asarray(x)
    got = [limbs8_to_int(xn[:, i]) for i in range(8)]
    assert got == ref, f"mismatch {got[:2]} vs {ref[:2]}"
    print("fmul8 exact over 50-deep chain: OK")
    # also bounds check: max limb after carry
    print("max loose limb:", float(np.asarray(x).max()))

    for B in (2048, 4096, 8192, 16384):
        key = jax.random.PRNGKey(0)
        a32 = jax.random.randint(key, (NL8, B), 0, 256, jnp.int32).astype(jnp.float32)
        b32 = jax.random.randint(key, (NL8, B), 0, 256, jnp.int32).astype(jnp.float32)
        ai = jax.random.randint(key, (E.NLIMB, B), 0, 32768, dtype=jnp.int32)
        bi = jax.random.randint(key, (E.NLIMB, B), 0, 32768, dtype=jnp.int32)
        f = slope(fmul8, a32, b32)
        i = slope(E.fmul, ai, bi)
        print(f"B={B}: fp32r8 {f:.1f} us/fmul ({f/B*1e3:.1f} ns/sig-mul), int32r15 {i:.1f} us/fmul")


if __name__ == "__main__":
    main()
