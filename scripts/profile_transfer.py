"""Decompose verify-batch time: device-resident compute vs host transfer
on this (tunneled) TPU."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tendermint_tpu.ops import ed25519 as E
from tendermint_tpu.crypto import ed25519 as ed

B = 8192


def t(msg, f, reps=5):
    f()
    t0 = time.perf_counter()
    for _ in range(reps):
        f()
    el = (time.perf_counter() - t0) / reps
    print(f"{msg}: {el*1e3:.1f} ms")
    return el


def main():
    print(jax.devices()[0], file=sys.stderr)

    # roundtrip latency floor: tiny transfer both ways
    one = np.zeros(8, np.int32)
    t("tiny roundtrip (device_put + asarray)", lambda: np.asarray(jnp.asarray(one) + 1))

    big = np.zeros((17, B), np.int32)
    t("557KB host->device (device_put, sync'd by tiny readback)",
      lambda: np.asarray(jax.device_put(big)[0, :8]))
    dev = jax.device_put(big)
    t("557KB device->host", lambda: np.asarray(dev))

    # real verify batch, data pre-staged on device
    seeds = [bytes([i]) * 32 for i in range(64)]
    pubs = [ed.public_key(s) for s in seeds]
    items = []
    for i in range(B):
        k = i % 64
        msg = b"m%d-%d" % (i, k)
        items.append((pubs[k], msg, ed.sign(seeds[k], msg)))

    prep = E.prepare_batch_limbs(items, B)
    host_args = prep[:6]
    dev_args = tuple(jax.device_put(np.asarray(a)) for a in host_args)

    # compile
    np.asarray(E._verify_jit(*dev_args))

    e_resident = t("verify: device-resident args + bool readback",
                   lambda: np.asarray(E._verify_jit(*dev_args)), reps=3)
    e_host = t("verify: host args (transfer included)",
               lambda: np.asarray(E._verify_jit(*[jnp.asarray(a) for a in host_args])), reps=3)
    print(f"-> transfer share: {(e_host-e_resident)*1e3:.0f} ms")

    # marshaling cost on host
    t0 = time.perf_counter()
    E.prepare_batch_limbs(items, B)
    print(f"host marshal (prepare_batch_limbs): {(time.perf_counter()-t0)*1e3:.0f} ms")


if __name__ == "__main__":
    main()
