"""Dev tool: raw elementwise throughput of int32 mul vs fp32 mul vs bf16
matmul on the local device — picks the arithmetic substrate for the
Ed25519 limb kernels."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 17 * 8192  # same element count as one limb-major field element batch
REPS = 200


def bench(name, fn, *args):
    fn(*args).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(REPS):
        o = fn(*args)
    o.block_until_ready()
    el = time.perf_counter() - t0
    print(f"{name}: {el/REPS*1e6:.1f} us/op")


def main():
    print(jax.devices()[0], file=sys.stderr)
    key = jax.random.PRNGKey(0)
    a_i = jax.random.randint(key, (N,), 0, 32768, dtype=jnp.int32)
    b_i = jax.random.randint(key, (N,), 0, 32768, dtype=jnp.int32)
    a_f = a_i.astype(jnp.float32)
    b_f = b_i.astype(jnp.float32)

    # chains of K dependent multiplies to avoid measuring dispatch
    K = 64

    @jax.jit
    def chain_i32(a, b):
        x = a
        for _ in range(K):
            x = (x * b) & 0x7FFF
        return x

    @jax.jit
    def chain_f32(a, b):
        x = a
        for _ in range(K):
            x = x * b + a
        return x

    @jax.jit
    def chain_i32_addshift(a, b):
        x = a
        for _ in range(K):
            x = (x + b) >> 1
        return x

    @jax.jit
    def chain_i16_mul(a, b):
        x = a.astype(jnp.int16)
        bb = b.astype(jnp.int16)
        for _ in range(K):
            x = x * bb
        return x.astype(jnp.int32)

    bench(f"int32 mul+mask x{K} over {N}", chain_i32, a_i, b_i)
    bench(f"fp32 fma x{K} over {N}", chain_f32, a_f, b_f)
    bench(f"int32 add+shift x{K} over {N}", chain_i32_addshift, a_i, b_i)
    bench(f"int16 mul x{K} over {N}", chain_i16_mul, a_i, b_i)

    # MXU: bf16 matmul throughput reference
    M = 1024
    am = jax.random.normal(key, (M, M), dtype=jnp.bfloat16)
    bm = jax.random.normal(key, (M, M), dtype=jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        x = a
        for _ in range(8):
            x = jnp.dot(x, b, preferred_element_type=jnp.float32).astype(jnp.bfloat16)
        return x

    bench("bf16 1024^3 matmul x8", mm, am, bm)


if __name__ == "__main__":
    main()
