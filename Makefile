# Targets mirror the reference Makefile's test tiers
# (/root/reference/Makefile:27-39): `test` = unit suite, `test_race` =
# the race-discipline tier (lock-order-graph instrumentation — the
# Python analogue of `go test -race`, see libs/racecheck.py),
# `test_integrations` = the multi-node network scenarios.
#
# The reference's integration tier runs in docker containers
# (test/p2p/test.sh, test/docker/). Containers are OUT OF ENVIRONMENTAL
# SCOPE here — no docker daemon exists in this environment — so
# test_integrations runs the process tier: the same six scenarios
# (basic, atomic_broadcast, fast_sync, kill_all, seeds, pex) as real
# node processes over real TCP with real SIGKILL crash semantics
# (test/p2p/scenarios.py; see test/p2p/README.md). The authored docker
# tier (test/p2p/run_docker.sh) remains for docker-capable hosts.

PY ?= python

test:
	$(PY) -m pytest tests/ -q

test_race:
	$(PY) -m pytest tests/test_race.py -q

test_integrations:
	$(PY) test/p2p/scenarios.py

test_slow:
	$(PY) -m pytest tests/ -q -m slow

native:
	$(MAKE) -C native

.PHONY: test test_race test_integrations test_slow native
