# Targets mirror the reference Makefile's test tiers
# (/root/reference/Makefile:27-39): `test` = unit suite, `test_race` =
# the race-discipline tier (lock-order-graph instrumentation — the
# Python analogue of `go test -race`, see libs/racecheck.py),
# `test_integrations` = the multi-node network scenarios.
#
# The reference's integration tier runs in docker containers
# (test/p2p/test.sh, test/docker/). Containers are OUT OF ENVIRONMENTAL
# SCOPE here — no docker daemon exists in this environment — so
# test_integrations runs the process tier: the same six scenarios
# (basic, atomic_broadcast, fast_sync, kill_all, seeds, pex) as real
# node processes over real TCP with real SIGKILL crash semantics
# (test/p2p/scenarios.py; see test/p2p/README.md). The authored docker
# tier (test/p2p/run_docker.sh) remains for docker-capable hosts.

PY ?= python
# tier1 uses bash process features (PIPESTATUS); everything else is sh-safe
SHELL := /bin/bash

test:
	$(PY) -m pytest tests/ -q

# The ROADMAP.md tier-1 verify command, verbatim — the bar every PR must
# hold (dots no worse than the seed) — plus the chip-free hash-stream
# smoke (the two asserted BENCH_r07 rows: streamed hash offload >= 1.3x
# single-shot on the sim transport, flat host builder >= 1.5x recursive).
tier1: hash-stream-smoke chaos-smoke wal-torture-smoke statesync-smoke statetree-smoke metrics-smoke net-chaos-smoke wan-smoke pipeline-smoke fleet-smoke committee-smoke txtrace-smoke retention-smoke localnet-smoke shard-smoke upgrade-smoke overload-smoke replica-smoke
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# Chip-free bench smoke: every BASELINE config on the pinned CPU backend,
# so a transport/serving-path regression fails fast without hardware
# (bench_devd_stream asserts the streamed-vs-single-shot win;
# bench_partset asserts the hash-stream + flat-builder wins).
bench-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu $(PY) benches/run_all.py

# Hash-plane smoke, chip-free and fast (~30 s): only bench_partset's two
# asserted rows — sim-transport hash_stream and the flat host builder —
# with no jax offload compile. Runs as part of `make tier1`.
hash-stream-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_PARTSET_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_partset.py

# Chaos smoke, chip-free and fast (~30 s): a reduced FaultPlan pass of
# bench_chaos.py — breaker-open degraded throughput + recovery-time
# floor after daemon kill/restart. Runs as part of `make tier1` (the
# full fault matrix lives in tests/test_chaos_devd.py, incl. the
# slow-marked 20-block soak).
chaos-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_CHAOS_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_chaos.py

# WAL torture smoke, chip-free BY CONSTRUCTION (~10 s): bench_wal.py's
# reduced pass — group-commit >= 1.3x fsync-per-record floor, repair scan
# on a torn 10k-record log, and a byte-offset truncation sweep over the
# tail records, every offset recovering (the full crash-model tiers live
# in tests/test_wal_repair.py + tests/test_wal_torture.py, incl. the
# slow-marked subprocess sweep). Runs as part of `make tier1`.
wal-torture-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_WAL_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_wal.py

# State-sync smoke, chip-free (~30 s): bench_statesync.py's reduced pass —
# one producer -> light-verified restore round trip on a signedkv chain
# with an injected corrupt chunk REJECTED, restore-vs-replay, and the
# sim-transport streamed chunk-verify floor (>=1.3x). Runs as part of
# `make tier1` (the protocol/reactor matrix lives in
# tests/test_statesync.py, incl. the slow-marked 1k-block restore soak).
statesync-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_STATESYNC_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_statesync.py

# State-tree smoke, chip-free (~20 s): bench_statetree.py's reduced pass —
# authenticated-tree build + incremental-commit-vs-rebuild floor, proof
# correctness rows (membership/absence verify, tamper/wrong-root refused),
# and a full->delta snapshot round trip with an injected corrupt chunk
# REJECTED (the full matrix lives in tests/test_statetree.py +
# tests/test_statesync_delta.py). Runs as part of `make tier1`.
statetree-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_STATETREE_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_statetree.py

# Network chaos smoke, chip-free (~40 s): bench_netchaos.py's reduced
# pass — a 4-node REAL-TCP testnet (in-repo SecretConnection on every
# link, ops/netfaults proxies in the middle) commits through one
# partition-heal cycle + one listener churn, recovery time asserted and
# final state byte-identical (the full scenario matrix lives in
# tests/test_netchaos.py, incl. the slow-marked 5-node soak). Runs as
# part of `make tier1`.
net-chaos-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_NETCHAOS_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_netchaos.py

# WAN/adversary smoke, chip-free (~60 s): bench_wan.py's reduced pass —
# a 4-node real-TCP signedkv net under ONE seeded WAN profile
# (continental latency/jitter/loss via ops/netfaults WanProfile) with
# heights/s + commit skew recorded off the ops/fleet timelines, then one
# mempool flood burst: a hostile peer pushes garbage signatures at the
# sig gate, the shed asserted visible in telemetry and the commit
# cadence asserted >= 1/3 of baseline, final state byte-identical (the
# full profile matrix + adversary catalog lives in tests/test_netchaos.py,
# incl. the slow-marked WAN soak). Runs as part of `make tier1`.
wan-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_WAN_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_wan.py

# Pipeline smoke, chip-free (~10 s): bench_pipeline.py's reduced pass —
# a real single-validator durable chain committing the same deterministic
# signed workload on the seed execution plane vs the round-14 pipelined
# plane: per-height byte-identity (block hash / part-set root / app hash
# / txs) asserted across runs, the committed-tx/s floor asserted, and
# the sharded kvstore fold's VersionedTree root asserted byte-identical
# to serial apply. Runs as part of `make tier1` (the full matrix lives
# in tests/test_pipeline.py + the pipeline crash tiers in
# tests/test_wal_torture.py).
pipeline-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_PIPELINE_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_pipeline.py

# Fleet observability smoke, chip-free (~40 s): bench_fleet.py's reduced
# pass — a 4-node real-TCP net scraped by ops/fleet (GET /metrics +
# consensus_trace + GET /health only): per-height cross-node timeline
# reconstructed (propagation lag / quorum-formation time / commit skew),
# the partition arm detected and healed purely off /health, and the
# round-15 per-peer instrumentation overhead bounded <2% à la BENCH_r11
# (the full scenario matrix lives in tests/test_netchaos.py). Runs as
# part of `make tier1`.
fleet-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_FLEET_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_fleet.py

# Big-committee smoke, chip-free (~10 s): bench_committee.py's reduced
# pass — a LIVE 100-validator consensus run (in-process committee pump)
# batched vs per-vote vote verification with per-height byte-identity
# (block hash / part-set root / app hash) asserted and batched >= 1.3x
# per-vote blocks/s asserted, plus the commit-verify and
# aggregate-commit object rows at 4/100 validators (the full 4-400
# matrix writes BENCH_r16.json). Runs as part of `make tier1`.
committee-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_COMMITTEE_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_committee.py

# Telemetry smoke, chip-free (~20 s): bench_telemetry.py's reduced pass —
# boot a node, scrape GET /metrics (valid 0.0.4 text, >= 40 families
# spanning every plane), pull one consensus_trace (segments sum to the
# height wall clock within 5%), and the hot-path instrumentation
# overhead guard on the mempool signed-burst gate (asserted <2%).
# Runs as part of `make tier1`.
metrics-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_TELEMETRY_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_telemetry.py

# Tx-lifecycle tracing smoke, chip-free (~45 s): bench_txtrace.py's
# reduced pass — the per-tx span recorder on a live committing node
# (every completed trace's spans-through-commit asserted to sum within
# 10% of its measured end-to-end commit latency), the tracing +
# flight-recorder overhead bound on the mempool signed-burst shape
# asserted <2%, and a flight-record wedge dump written + parsed back.
# Runs as part of `make tier1` (the contract matrix lives in
# tests/test_txtrace.py + tests/test_flightrec.py; the netchaos
# partition wedge-diagnosis scenario in tests/test_netchaos.py).
txtrace-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_TXTRACE_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_txtrace.py

# Retention smoke, chip-free (~60 s): bench_retention.py's reduced pass
# — the ~200-height bounded-retention run: a live sqlite-backed node
# with [pruning] + the statesync producer armed vs an archive twin,
# steady-state disk bytes/height asserted bounded by retention (ratio
# floor), then the adversarial statesync offerer burst: forged-manifest,
# corrupt-chunk, and stalling offerers each BANNED (scrape-visible,
# latency recorded) while a joining node's restore completes from the
# honest source. Runs as part of `make tier1` (the slow retention soak +
# offerer matrix under WAN live in tests/test_netchaos.py; the crash
# tier in tests/test_retention.py).
retention-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_RETENTION_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_retention.py

# — the hundreds-of-nodes localnet tier, smoke-sized: a 5-node fleet of
# REAL node processes (ops/localnet) peered through netfaults link
# proxies converges byte-identically and reports its duplicate-vote
# ratio off live scrapes (~60 s; the 10/25/50-node scale ladder +
# dedup A/B + process-scale partition-heal run on the full bench).
localnet-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_LOCALNET_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_localnet.py

# Sharded-device-plane smoke, chip-free (~30 s): bench_devd_shard.py's
# reduced pass — 1-vs-2 sim daemon fleets behind ops/devd_shard with the
# aggregate sigs/s scaling floor asserted (>= 1.6x at 2 daemons), digest
# parity across fleet sizes, and the kill-one-mid-burst failover row:
# SIGKILL one of two daemons with a batch in flight, every lane keeps
# its exact verdict through re-dispatch, the dead endpoint's breaker
# opens and re-closes after restart. Runs as part of `make tier1` (the
# 1/2/4 ladder writes BENCH_r21.json; the chaos matrix lives in
# tests/test_chaos_devd.py + tests/test_devd_shard.py).
shard-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_DEVD_SHARD_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_devd_shard.py

# Upgrade-at-height smoke, chip-free (~60-90 s): bench_upgrade.py's
# reduced pass — ONE 4-process localnet rolling-upgraded across the
# genesis commit-format flip (laggard SIGKILLed before H, survivors
# cross without missing a height, laggard catches up through both
# formats, per-height byte identity both sides of H, upgrade_* scrape
# asserts, zero schedule refusals). Runs as part of `make tier1`; the
# full bench adds the wire/verify A-B at 100/400 validators and the
# flip-stall row, and writes BENCH_r22.json (docs/upgrade.md).
upgrade-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_UPGRADE_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_upgrade.py

# Overload-control smoke, chip-free (~90 s): bench_overload.py's reduced
# pass — ONE 4-process localnet where node 0 is flooded with bulk writes,
# hot reads, and two deliberately-slow WS subscribers while the scenario
# asserts consensus cadence stays within 1.5x the unloaded baseline,
# sheds are scrape-visible (rpc_shed_total / mempool_lane_full_total /
# ws_evictions_total), a priority probe commits ahead of a bulk marker
# submitted before it, the ladder transition lands in the flight ring,
# and per-height byte identity holds. Runs as part of `make tier1`; the
# full bench adds an n=6 row and writes BENCH_r23.json (docs/serving.md).
overload-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_OVERLOAD_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_overload.py

# Read-replica smoke, chip-free (~60-90 s): bench_replica.py's reduced
# pass — the replica_flood scenario on ONE 4-process localnet with two
# verified replica processes (plus one TAMPERING one) behind node 0. A
# hot verified-read flood + WS subscribers land on the replicas while
# the scenario asserts the validator's commit cadence stays flat,
# replica-served blocks are byte-identical to the validator's, the
# replica_* scrape rows move with zero proof failures, and a verifying
# client rejects 100% of reads from the tampered replica. Runs as part
# of `make tier1`; the full bench adds the 1/2/4-replica serving ladder
# and writes BENCH_r24.json (docs/serving.md § Read replicas).
replica-smoke:
	JAX_PLATFORMS=cpu TENDERMINT_TPU_PLATFORM=cpu BENCH_REPLICA_SMOKE=1 timeout -k 10 300 $(PY) benches/bench_replica.py

test_race:
	$(PY) -m pytest tests/test_race.py -q

test_integrations:
	$(PY) test/p2p/scenarios.py

test_slow:
	$(PY) -m pytest tests/ -q -m slow

native:
	$(MAKE) -C native

.PHONY: test test_race test_integrations test_slow native tier1 bench-smoke hash-stream-smoke chaos-smoke wal-torture-smoke statesync-smoke statetree-smoke metrics-smoke net-chaos-smoke wan-smoke pipeline-smoke fleet-smoke committee-smoke txtrace-smoke retention-smoke localnet-smoke shard-smoke upgrade-smoke overload-smoke replica-smoke
